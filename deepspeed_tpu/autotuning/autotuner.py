"""Autotuner — searches ZeRO stage / micro-batch / remat configs for the
fastest training setup.

Capability parity with the reference's ``deepspeed/autotuning/autotuner.py``
(Autotuner.tune:421 — tuning spaces per ZeRO stage, micro-batch sweeps,
experiment scheduling, ranked results) + ``tuner/`` (grid / random /
model-based search). TPU reshape: an *experiment* is just a ds_config dict;
a *runner* executes it and returns metrics — in-process for tests and
notebook use (engine_runner), or a subprocess launching the user's training
script exactly like the reference's scheduler.py run_job (subprocess_runner;
the engine exits after ``end_profile_step`` writing its metric file when
DS_AUTOTUNING_METRIC_FILE is set).

Failed experiments (OOM, bad composition) score -inf and are kept in the
record with their error, matching the reference's error-result handling.
"""

from __future__ import annotations

import copy
import itertools
import json
import os
import random
import subprocess
import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import logger

METRIC_FILE_ENV = "DS_AUTOTUNING_METRIC_FILE"


@dataclass
class Experiment:
    name: str
    config: Dict[str, Any]
    metrics: Optional[Dict[str, float]] = None
    error: Optional[str] = None
    overrides: Optional[Dict[str, Any]] = None
    slot: Optional[Dict[str, Any]] = None       # reservation it ran on

    @property
    def score(self) -> float:
        if self.metrics is None:
            return float("-inf")
        return self.metrics.get("throughput", float("-inf"))


def default_tuning_space(base_config: Dict[str, Any],
                         micro_batch_sizes: Optional[List[int]] = None,
                         zero_stages: Optional[List[int]] = None,
                         remat: Optional[List[bool]] = None) -> Dict[str, List]:
    """The reference's DEFAULT_TUNING_SPACE equivalent: per-ZeRO-stage spaces
    x micro-batch ladder x activation checkpointing."""
    mbs = micro_batch_sizes or [1, 2, 4, 8, 16]
    stages = zero_stages if zero_stages is not None else [0, 1, 2, 3]
    return {
        "train_micro_batch_size_per_gpu": mbs,
        "zero_optimization.stage": stages,
        "activation_checkpointing": remat if remat is not None else [False],
    }


def _set_path(cfg: Dict, dotted: str, value):
    parts = dotted.split(".")
    node = cfg
    for p in parts[:-1]:
        node = node.setdefault(p, {})
    node[parts[-1]] = value


class GridSearchTuner:
    """reference: tuner/index_based_tuner.py GridSearchTuner."""

    def __init__(self, space: Dict[str, List]):
        keys = list(space)
        self._combos = [dict(zip(keys, vals))
                        for vals in itertools.product(*(space[k] for k in keys))]

    def __iter__(self):
        return iter(self._combos)


class RandomTuner:
    """reference: tuner/index_based_tuner.py RandomTuner."""

    def __init__(self, space: Dict[str, List], num_trials: int = 50,
                 seed: int = 0):
        combos = list(GridSearchTuner(space))
        rng = random.Random(seed)
        rng.shuffle(combos)
        self._combos = combos[:num_trials]

    def __iter__(self):
        return iter(self._combos)


class ModelBasedTuner:
    """Cost-model-guided search (reference: tuner/model_based_tuner.py).

    The reference fits an XGBoost regressor over config-features ->
    throughput and repeatedly runs the predicted-best untried config.
    xgboost is not in this image, so the surrogate is closed-form ridge
    regression over the same featurization (numeric keys as log2 values,
    categorical keys one-hot) — enough to capture the monotone-ish
    throughput surfaces of this space.

    Protocol with Autotuner: ``num_seed`` shuffled combos are measured
    first; after every experiment Autotuner calls ``observe(overrides,
    score)``; each subsequent ``__next__`` refits and yields the untried
    combo with the best predicted score, for ``num_trials`` total.
    """

    def __init__(self, space: Dict[str, List], num_trials: int = 16,
                 num_seed: int = 4, seed: int = 0, ridge: float = 1e-3):
        import numpy as np
        self._np = np
        self._keys = list(space)
        self._space = space
        combos = list(GridSearchTuner(space))
        random.Random(seed).shuffle(combos)
        self._combos = combos
        self.num_trials = min(num_trials, len(combos))
        self.num_seed = min(num_seed, self.num_trials)
        self._obs_x: List = []
        self._obs_y: List[float] = []
        self._tried: List[Dict] = []
        self.ridge = ridge

    def _feat(self, overrides: Dict[str, Any]):
        np = self._np
        feats = [1.0]                                   # bias
        for k in self._keys:
            vals = self._space[k]
            v = overrides[k]
            if all(isinstance(x, bool) for x in vals):
                feats.append(float(v))
            elif all(isinstance(x, (int, float)) and not isinstance(x, bool)
                     for x in vals):
                feats.append(float(np.log2(float(v) + 1.0)))
            else:                                       # categorical one-hot
                feats.extend(1.0 if v == x else 0.0 for x in vals)
        return np.asarray(feats, np.float64)

    def observe(self, overrides: Dict[str, Any], score: float) -> None:
        if score == float("-inf"):                      # failed run
            score = 0.0
        self._obs_x.append(self._feat(overrides))
        self._obs_y.append(float(score))

    def _predict_best(self) -> Optional[Dict[str, Any]]:
        np = self._np
        remaining = [c for c in self._combos if c not in self._tried]
        if not remaining:
            return None
        if len(self._obs_y) < 2:
            return remaining[0]
        X = np.stack(self._obs_x)
        y = np.asarray(self._obs_y)
        d = X.shape[1]
        w = np.linalg.solve(X.T @ X + self.ridge * np.eye(d), X.T @ y)
        preds = [float(self._feat(c) @ w) for c in remaining]
        return remaining[int(np.argmax(preds))]

    def __iter__(self):
        for i in range(self.num_trials):
            nxt = (self._combos[i] if i < self.num_seed
                   else self._predict_best())
            if nxt is None:
                return
            self._tried.append(nxt)
            yield nxt


class Autotuner:
    """Experiment loop: generate -> run -> rank (reference autotuner.py:421).

    runner(config_dict) -> metrics dict with at least {"throughput"} (samples
    per second); raise or return None for a failed experiment.
    """

    def __init__(self,
                 base_config: Dict[str, Any],
                 runner: Callable[..., Optional[Dict[str, float]]],
                 tuning_space: Optional[Dict[str, List]] = None,
                 tuner_type: str = "gridsearch",
                 num_trials: int = 50,
                 early_stopping: int = 0,
                 results_dir: Optional[str] = None,
                 resource_slots: Optional[List[Dict[str, Any]]] = None,
                 kill_factor: float = 3.0):
        self.base_config = base_config
        self.runner = runner
        self.space = tuning_space or default_tuning_space(base_config)
        if tuner_type in ("gridsearch", "grid"):
            self.tuner = GridSearchTuner(self.space)
        elif tuner_type == "random":
            self.tuner = RandomTuner(self.space, num_trials)
        elif tuner_type in ("model", "model_based"):
            self.tuner = ModelBasedTuner(self.space, num_trials)
        else:
            raise ValueError(f"unknown tuner_type '{tuner_type}' "
                             "(gridsearch | random | model)")
        self.early_stopping = early_stopping
        self.results_dir = results_dir
        self.experiments: List[Experiment] = []
        # parallel mode (reference scheduler.py:114,319): experiments run
        # concurrently over reserved slots, losing configs killed
        self.resource_slots = resource_slots
        self.kill_factor = kill_factor

    def _materialize(self, overrides: Dict[str, Any]) -> Dict[str, Any]:
        cfg = copy.deepcopy(self.base_config)
        for dotted, val in overrides.items():
            if dotted == "activation_checkpointing":
                _set_path(cfg, "activation_checkpointing.partition_activations",
                          bool(val))
            else:
                _set_path(cfg, dotted, val)
        # micro batch sweeps re-derive gas from the fixed global batch
        if "train_micro_batch_size_per_gpu" in overrides and \
                "train_batch_size" in cfg:
            cfg.pop("gradient_accumulation_steps", None)
        return cfg

    def _make_exp(self, overrides) -> Experiment:
        name = "exp_" + "_".join(
            f"{k.split('.')[-1]}{v}" for k, v in overrides.items())
        return Experiment(name=name, config=self._materialize(overrides),
                          overrides=overrides)

    def _record(self, exp: Experiment, best: float, since_best: int):
        """Shared per-experiment bookkeeping: observe, log, early-stop
        accounting. Returns (best, since_best)."""
        self.experiments.append(exp)
        if hasattr(self.tuner, "observe"):              # model-based feedback
            self.tuner.observe(exp.overrides, exp.score)
        logger.info("autotuning %s -> %s", exp.name,
                    exp.metrics or exp.error)
        if exp.score > best:
            return exp.score, 0
        return best, since_best + 1

    def _finish(self) -> List[Experiment]:
        self.experiments.sort(key=lambda e: e.score, reverse=True)
        if self.results_dir:
            self.write_results(self.results_dir)
        return self.experiments

    def tune(self) -> List[Experiment]:
        if self.resource_slots and len(self.resource_slots) > 1:
            return self._tune_parallel()
        best = float("-inf")
        since_best = 0
        for overrides in self.tuner:
            exp = self._make_exp(overrides)
            try:
                exp.metrics = self.runner(exp.config)
            except Exception as e:  # OOM / invalid composition: record + go on
                exp.error = f"{type(e).__name__}: {e}"
                logger.warning("autotuning experiment %s failed: %s",
                               exp.name, exp.error[:200])
            best, since_best = self._record(exp, best, since_best)
            if self.early_stopping and since_best >= self.early_stopping:
                logger.info("autotuning early stop after %d stale trials",
                            since_best)
                break
        return self._finish()

    def _tune_parallel(self) -> List[Experiment]:
        """Waved concurrency: up to n_slots candidates in flight, results
        fed back to the tuner between waves (model-based feedback still
        steers), stale-wave early stop preserved. The scheduler records
        runner failures into exp.error itself."""
        from .scheduler import ParallelScheduler
        sched = ParallelScheduler(self.runner, self.resource_slots,
                                  kill_factor=self.kill_factor)
        n = sched.rm.n_slots
        best = float("-inf")
        since_best = 0
        it = iter(self.tuner)
        done = False
        while not done:
            wave = []
            for _ in range(n):
                try:
                    wave.append(self._make_exp(next(it)))
                except StopIteration:
                    done = True
                    break
            if not wave:
                break
            sched.run_wave(wave)
            for exp in wave:
                best, since_best = self._record(exp, best, since_best)
            if self.early_stopping and since_best >= self.early_stopping:
                logger.info("autotuning early stop after %d stale trials",
                            since_best)
                break
        return self._finish()

    def best(self) -> Optional[Experiment]:
        return self.experiments[0] if self.experiments else None

    def write_results(self, results_dir: str) -> str:
        os.makedirs(results_dir, exist_ok=True)
        path = os.path.join(results_dir, "autotuning_results.json")
        with open(path, "w") as f:
            json.dump([{"name": e.name, "metrics": e.metrics,
                        "error": e.error, "config": e.config}
                       for e in self.experiments], f, indent=2)
        best = self.best()
        if best and best.metrics is not None:
            with open(os.path.join(results_dir, "best_config.json"), "w") as f:
                json.dump(best.config, f, indent=2)
        return path


# ---------------------------------------------------------------------------
# runners
# ---------------------------------------------------------------------------

def engine_runner(model_factory: Callable[[], Any],
                  batch_factory: Callable[[int], Any],
                  steps: int = 5,
                  warmup: int = 2) -> Callable[[Dict], Dict[str, float]]:
    """In-process experiment runner: builds a fresh engine per config, times
    `steps` train_batches. batch_factory(step) -> global batch."""
    import time

    import jax

    def run(config: Dict) -> Dict[str, float]:
        import deepspeed_tpu as ds
        cfg = copy.deepcopy(config)
        act = cfg.get("activation_checkpointing", {})
        model = model_factory()
        if act.get("partition_activations") and hasattr(model, "cfg"):
            import dataclasses
            model = type(model)(dataclasses.replace(model.cfg, remat=True))
        engine, *_ = ds.initialize(model=model, config=cfg,
                                   example_batch=batch_factory(0))
        for i in range(warmup):
            engine.train_batch(batch_factory(i))
        t0 = time.perf_counter()
        loss = None
        for i in range(steps):
            loss = engine.train_batch(batch_factory(warmup + i))["loss"]
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / steps
        bs = engine.train_batch_size
        return {"throughput": bs / dt, "step_time": dt,
                "train_batch_size": bs}

    return run


def subprocess_runner(cmd: List[str], exps_dir: str,
                      timeout: int = 1800) -> Callable[[Dict], Dict[str, float]]:
    """Script-mode runner (reference: scheduler.py run_job): writes the exp
    ds_config, launches `cmd + ['--deepspeed_config', path]`, and reads the
    metric file the engine writes at end_profile_step."""

    import itertools
    os.makedirs(exps_dir, exist_ok=True)
    # offset past any previous session's records in a reused exps_dir (the
    # per-run counter keeps concurrent threads collision-free)
    counter = itertools.count(
        sum(1 for f in os.listdir(exps_dir) if f.endswith("_config.json")))
    lock = threading.Lock()

    def run(config: Dict, slot: Optional[Dict] = None,
            deadline: Optional[Callable[[], Optional[float]]] = None
            ) -> Dict[str, float]:
        with lock:
            n = next(counter)
        cfg_path = os.path.join(exps_dir, f"exp_{n}_config.json")
        metric_path = os.path.join(exps_dir, f"exp_{n}_metrics.json")
        cfg = copy.deepcopy(config)
        cfg.setdefault("autotuning", {})["enabled"] = True
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)
        if os.path.exists(metric_path):
            os.unlink(metric_path)      # a stale file from a previous
                                        # session must not score this run
        env = dict(os.environ, **{METRIC_FILE_ENV: metric_path})
        if slot:
            # pin the launch to its reservation (parallel scheduler):
            # device slots restrict the runtime's visible accelerators
            # (TPU + CUDA spellings so the child's backend picks it up),
            # host slots carry explicit env
            if slot.get("devices"):
                dev = str(slot["devices"])
                env["DSTPU_SLOT_DEVICES"] = dev
                env["TPU_VISIBLE_CHIPS"] = dev
                env["TPU_VISIBLE_DEVICES"] = dev
                env["CUDA_VISIBLE_DEVICES"] = dev
            env.update(slot.get("env") or {})
        out_path = os.path.join(exps_dir, f"exp_{n}_output.log")
        out_f = open(out_path, "w")
        # file-backed output: PIPEs would need draining while we poll (a
        # chatty child fills the ~64KB pipe buffer and deadlocks)
        proc = subprocess.Popen(cmd + ["--deepspeed_config", cfg_path],
                                env=env, stdout=out_f,
                                stderr=subprocess.STDOUT, text=True)
        # poll so a losing config is killed as soon as its deadline expires
        # (a pre-launch budget would never bind for the first wave, when no
        # experiment has completed yet)
        import time as _time
        t0 = _time.monotonic()
        while True:
            try:
                proc.wait(timeout=2.0)
                break
            except subprocess.TimeoutExpired:
                pass
            rem = deadline() if deadline is not None else None
            if (rem is not None and rem <= 0) or                     _time.monotonic() - t0 > timeout:
                proc.kill()
                proc.wait()
                out_f.close()
                raise RuntimeError(
                    "experiment killed: losing config (exceeded the "
                    "scheduler deadline)" if rem is not None and rem <= 0
                    else f"experiment timed out after {timeout}s")
        out_f.close()
        if not os.path.exists(metric_path):
            with open(out_path) as f:
                tail = f.read()[-1000:]
            raise RuntimeError(
                f"experiment produced no metric file (rc={proc.returncode}): "
                f"{tail}")
        with open(metric_path) as f:
            return json.load(f)

    return run
