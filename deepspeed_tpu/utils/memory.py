"""Memory introspection — see_memory_usage for TPU + host.

Capability parity with the reference's ``utils/see_memory_usage``
(runtime/utils.py: cuda allocated/reserved + host RSS logging at tagged
points). TPU edition: per-device live-buffer bytes from
``device.memory_stats()`` (PJRT exposes bytes_in_use/peak) + host RSS from
/proc, same call shape: ``see_memory_usage("after step", force=True)``.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax

from .logging import logger


def host_rss_bytes() -> int:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def device_memory_stats() -> Dict[str, int]:
    """Summed live/peak bytes over addressable devices (0s when the backend
    doesn't expose memory_stats, e.g. CPU)."""
    in_use = peak = limit = 0
    for d in jax.local_devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except (RuntimeError, AttributeError):
            pass
        in_use += stats.get("bytes_in_use", 0)
        peak += stats.get("peak_bytes_in_use", 0)
        limit += stats.get("bytes_limit", 0)
    return {"bytes_in_use": in_use, "peak_bytes_in_use": peak,
            "bytes_limit": limit}


def see_memory_usage(message: str, force: bool = False,
                     ranks=(0,)) -> Optional[Dict[str, float]]:
    """Log device + host memory at a tagged point; returns the numbers.
    ``force=False`` mirrors the reference's no-op default so call sites can
    stay in production code."""
    if not force:
        return None
    if jax.process_index() not in ranks:
        return None
    dev = device_memory_stats()
    gb = 1024 ** 3
    out = {"device_GB": dev["bytes_in_use"] / gb,
           "device_peak_GB": dev["peak_bytes_in_use"] / gb,
           "device_limit_GB": dev["bytes_limit"] / gb,
           "host_rss_GB": host_rss_bytes() / gb}
    logger.info(
        "MEM %s | device %.2fGB (peak %.2fGB / limit %.2fGB) | host RSS %.2fGB",
        message, out["device_GB"], out["device_peak_GB"],
        out["device_limit_GB"], out["host_rss_GB"])
    return out
