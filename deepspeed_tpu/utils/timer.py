"""Wall-clock + throughput timers.

Capability parity with the reference's ``deepspeed/utils/timer.py``
(SynchronizedWallClockTimer, ThroughputTimer). "Synchronized" here means
blocking on the last dispatched jax computation (block_until_ready) rather
than cuda events.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List, Optional

import jax


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.elapsed_ = 0.0
        self.started = False
        self._start_t = 0.0
        self.count = 0

    def start(self):
        self.started = True
        self._start_t = time.time()

    def stop(self, sync=None):
        if not self.started:
            return
        if sync is not None:
            jax.block_until_ready(sync)
        self.elapsed_ += time.time() - self._start_t
        self.started = False
        self.count += 1

    def elapsed(self, reset: bool = True) -> float:
        e = self.elapsed_
        if reset:
            self.elapsed_ = 0.0
            self.count = 0
        return e

    def mean(self) -> float:
        return self.elapsed_ / max(self.count, 1)


class SynchronizedWallClockTimer:
    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True) -> str:
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}ms")
        out = " | ".join(parts)
        if out:
            from .logging import log_dist
            log_dist(out, ranks=[0])
        return out


class ThroughputTimer:
    """Samples/sec + TFLOPs estimation. reference: utils/timer.py ThroughputTimer."""

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: Optional[int] = None,
                 model_flops_per_sample: Optional[float] = None):
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.model_flops_per_sample = model_flops_per_sample
        self.epoch_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self._start_t = None

    def start(self):
        self._start_t = time.time()

    def stop(self, sync=None, report_speed: bool = True):
        if self._start_t is None:
            return
        if sync is not None:
            jax.block_until_ready(sync)
        self.global_step_count += 1
        if self.global_step_count > self.start_step:
            self.total_elapsed_time += time.time() - self._start_t
        self._start_t = None

    @property
    def avg_samples_per_sec(self) -> float:
        steps = max(self.global_step_count - self.start_step, 1)
        if self.total_elapsed_time == 0:
            return 0.0
        return steps * self.batch_size / self.total_elapsed_time

    @property
    def avg_tflops(self) -> Optional[float]:
        if self.model_flops_per_sample is None:
            return None
        return self.avg_samples_per_sec * self.model_flops_per_sample / 1e12
