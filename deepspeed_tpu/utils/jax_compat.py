"""jax version compatibility shims (opt-in: DSTPU_JAX_COMPAT=1).

The package is written against the modern jax surface; older images (the
0.4.x line) lack some of it. Each shim forward-ports the missing API so
call sites stay canonical — graftlint's jit-scope analysis keys on the
``jax.shard_map`` spelling, and rewriting ~17 launch sites per jax
version would churn every shard_map region in the tree.

Opt-in rather than automatic: on the 0.4.x jaxlib the adapter unlocks
compile paths (qwZ+TP int8 gathers, the SPMD pipeline executor) that
crash INSIDE XLA compilation — `Fatal Python error: Aborted`, killing
the process. A missing attribute fails one test; an aborting compiler
kills the whole run. Set DSTPU_JAX_COMPAT=1 only on jaxlibs where the
unlocked paths are known-good.
"""

from __future__ import annotations

import inspect


def install_shard_map_compat() -> bool:
    """Alias ``jax.shard_map`` on versions that only ship
    ``jax.experimental.shard_map``, adapting the modern kwargs:

    - ``axis_names={...}`` (axes manual inside the region; the rest stay
      auto) -> the old ``auto=frozenset(all) - axis_names``;
    - ``check_vma=`` -> the old ``check_rep=``.

    Returns True when an alias was installed (False: native support)."""
    import jax
    if hasattr(jax, "shard_map"):
        return False
    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except ImportError:     # pragma: no cover - no shard_map at all
        return False
    legacy_params = inspect.signature(_legacy).parameters

    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=None, **kwargs):
        if axis_names is not None and "auto" in legacy_params:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kwargs["auto"] = auto
        if check_vma is not None:
            key = "check_rep" if "check_rep" in legacy_params else "check_vma"
            kwargs[key] = check_vma
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kwargs)

    jax.shard_map = shard_map
    return True

# NOTE: jax.lax.axis_size is deliberately NOT shimmed (psum(1, name) is
# the classic spelling): unlocking the qwZ+TP compile path on the 0.4.x
# jaxlib aborts the PROCESS inside XLA compilation — a clean
# AttributeError at trace time is strictly safer than a compiler crash
# that would kill an entire pytest run.


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=None):
    """Version-portable shard_map for the comm-plan collectives: native
    ``jax.shard_map`` when present, otherwise a CALL-LOCAL adaptation of
    ``jax.experimental.shard_map`` (``axis_names={...}`` -> the old
    ``auto=`` complement, ``check_vma`` -> ``check_rep``).

    Unlike :func:`install_shard_map_compat` this never mutates ``jax`` —
    only the call site that opted in rides the legacy API. The quantized
    reduce-scatter / all-to-all paths (runtime/comm/quantized.py) are
    fully-manual or manual-over-size->=1-DP-axes regions that were
    verified to compile on the 0.4.x jaxlib, unlike the qwZ+TP and
    SPMD-pipeline shapes the module docstring warns about."""
    import jax
    if hasattr(jax, "shard_map"):
        kwargs = {}
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _legacy
    legacy_params = inspect.signature(_legacy).parameters
    kwargs = {}
    if axis_names is not None and "auto" in legacy_params:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kwargs["auto"] = auto
    if check_vma is not None:
        key = "check_rep" if "check_rep" in legacy_params else "check_vma"
        kwargs[key] = check_vma
    return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kwargs)
