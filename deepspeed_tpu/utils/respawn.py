"""Clean-subprocess re-exec onto an N-device virtual CPU mesh.

One shared recipe (used by __graft_entry__.dryrun_multichip and the
benchmarks that need a multi-device mesh from a TPU-pinned process): the
current process's jax may already be initialized against a real backend by
a site hook, so multi-device CPU work must re-exec with a scrubbed
environment. Includes the raised CPU-collective rendezvous timeouts —
device threads timeshare the host cores, and arrival skew at a collective
can exceed the runtime's default 40s abort on big programs.
"""

from __future__ import annotations

import os
from typing import Dict


def clean_cpu_env(n_devices: int, base: Dict[str, str] = None,
                  collective_timeout_flags: bool = True
                  ) -> Dict[str, str]:
    """Environment for a subprocess that must see n_devices CPU devices.

    ``collective_timeout_flags=False`` drops the raised CPU-collective
    rendezvous timeouts: older jaxlibs hard-ABORT on unknown XLA_FLAGS
    ("Unknown flags in XLA_FLAGS", rc -6), so callers retry without them
    when the first launch dies that way (__graft_entry__.dryrun_multichip
    does)."""
    env = dict(base if base is not None else os.environ)
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f)
    flags += f" --xla_force_host_platform_device_count={n_devices}"
    if collective_timeout_flags:
        flags += (" --xla_cpu_collective_call_warn_stuck_timeout_seconds=300"
                  " --xla_cpu_collective_call_terminate_timeout_seconds=1200")
    env["XLA_FLAGS"] = flags.strip()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_PLATFORM_NAME", None)
    # a site hook may register a TPU PJRT plugin and force its platform;
    # drop the env vars that trigger it so the CPU platform wins
    for k in list(env):
        if k.startswith("PALLAS_AXON") or k.startswith("AXON_"):
            env.pop(k)
    return env
