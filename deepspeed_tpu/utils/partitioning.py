"""Tensor-parallel sharding rules: regex on param path -> PartitionSpec.

The reference delegates training TP to an external Megatron `mpu` object and
does inference TP by per-architecture weight-name policies
(module_inject/replace_policy.py). Here TP is first-class: models ship a rule
table mapping parameter-path patterns to PartitionSpecs over the "model" axis,
and this module applies it to a params pytree.
"""

from __future__ import annotations

import re
from typing import Dict, Optional

import jax
from jax.sharding import PartitionSpec as P


def path_str(path) -> str:
    """'transformer/h_0/attn/c_attn/kernel'-style key path string."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def build_tp_specs(params, rules: Optional[Dict[str, P]]):
    """Pytree of PartitionSpecs (or None) matching ``params``.

    ``rules`` maps regex patterns (searched against the /-joined path) to specs;
    first match wins, in insertion order. None → no TP sharding for that param.
    """
    compiled = [(re.compile(k), v) for k, v in (rules or {}).items()]

    def spec_for(path, leaf):
        s = path_str(path)
        for pat, spec in compiled:
            if pat.search(s):
                return spec
        return None

    return jax.tree_util.tree_map_with_path(spec_for, params)
