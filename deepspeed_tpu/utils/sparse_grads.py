"""Sparse embedding gradients — COO representation + bandwidth-lean sync.

Capability parity with the reference's ``deepspeed/runtime/sparse_tensor.py``
(SparseTensor) and the engine's sparse allreduce of embedding grads
(engine.py:2465-2547 sparse_allreduce_bucket: exchange only the touched
rows' indices+values, then scatter-add). On TPU the exchange is
all_gather of the fixed-size (ids, rows) pair over the data axis — wire
bytes scale with TOKENS touched instead of the full [V, H] table, the same
saving the reference gets from torch sparse tensors, with static shapes so
it jits.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp

PyTree = Any


@dataclasses.dataclass
class SparseTensor:
    """COO over the leading (row) dim (reference: sparse_tensor.py)."""
    indices: jnp.ndarray          # [n]
    values: jnp.ndarray           # [n, ...]
    dense_shape: Tuple[int, ...]

    def to_dense(self) -> jnp.ndarray:
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    @staticmethod
    def from_dense(dense: jnp.ndarray, indices: jnp.ndarray) -> "SparseTensor":
        return SparseTensor(indices=indices, values=dense[indices],
                            dense_shape=tuple(dense.shape))

    def sparse_size(self) -> int:
        return int(self.indices.size + self.values.size)


def embedding_grad_sparse(ids: jnp.ndarray, d_rows: jnp.ndarray,
                          vocab_size: int) -> SparseTensor:
    """Token ids [T] + per-token cotangents [T, H] -> sparse [V, H] grad.
    Duplicate ids keep duplicate entries (scatter-add resolves them), so
    shapes stay static under jit."""
    H = d_rows.shape[-1]
    return SparseTensor(indices=ids.reshape(-1),
                        values=d_rows.reshape(-1, H),
                        dense_shape=(vocab_size, H))


def sparse_allreduce(st: SparseTensor, axis: str) -> jnp.ndarray:
    """Cross-rank sum of sparse embedding grads -> dense table.

    Inside shard_map: all_gather the (ids, values) pairs (bytes ∝ tokens x
    H x ranks, vs V x H for a dense allreduce) and scatter-add locally.
    reference: engine.sparse_allreduce_bucket.
    """
    all_ids = jax.lax.all_gather(st.indices, axis, tiled=True)      # [R*n]
    all_vals = jax.lax.all_gather(st.values, axis, tiled=True)      # [R*n, H]
    out = jnp.zeros(st.dense_shape, st.values.dtype)
    return out.at[all_ids].add(all_vals)
