"""Rank-aware logging utilities.

Capability parity with the reference's ``deepspeed/utils/logging.py`` (log_dist,
rank-filtered logger); implemented against jax process indices instead of torch
distributed ranks.
"""

import logging
import os
import sys
from typing import Iterable, Optional

_LOGGER_NAME = "deepspeed_tpu"

_log_level = os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper()


def _create_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if logger.handlers:
        return logger
    logger.setLevel(getattr(logging, _log_level, logging.INFO))
    logger.propagate = False
    handler = logging.StreamHandler(stream=sys.stdout)
    handler.setFormatter(
        logging.Formatter("[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s",
                          datefmt="%Y-%m-%d %H:%M:%S"))
    logger.addHandler(handler)
    return logger


logger = _create_logger()


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the listed process ranks (None / [-1] = all)."""
    my_rank = _process_index()
    if ranks is None or -1 in list(ranks) or my_rank in list(ranks):
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message: str, _seen=set()) -> None:
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
