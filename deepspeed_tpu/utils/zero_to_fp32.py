"""zero_to_fp32 — consolidate a training checkpoint into one fp32 weights file.

Capability parity with the reference's ``utils/zero_to_fp32.py`` CLI (walk
the zero partitioned checkpoint, merge shards, emit a load_state_dict-able
file). Our checkpoints store whole name-keyed tensors already, so
consolidation = read the master (fp32) weights (falling back to the model
weights upcast) and write a single fp32 npz::

    python -m deepspeed_tpu.utils.zero_to_fp32 ckpt_dir output.npz [--tag T]
"""

from __future__ import annotations

import argparse
import os
from typing import Optional

import numpy as np


def convert_zero_checkpoint_to_fp32_state_dict(
        ckpt_dir: str, output_file: str, tag: Optional[str] = None) -> dict:
    from ..runtime.checkpointing import get_latest_tag, read_flat_npz
    if tag is None:
        tag = get_latest_tag(ckpt_dir)
        if tag is None:
            raise FileNotFoundError(f"no 'latest' tag in {ckpt_dir}")
    d = os.path.join(ckpt_dir, tag)
    optim = read_flat_npz(os.path.join(d, "optim_states.npz"))
    masters = {k[len("master/"):]: v for k, v in optim.items()
               if k.startswith("master/")}
    if not masters:
        # fp32 runs alias master into the model file
        masters = read_flat_npz(os.path.join(d, "model_states.npz"))
    state_dict = {k: np.asarray(v, np.float32) for k, v in masters.items()}
    np.savez(output_file, **state_dict)
    return state_dict


def main(argv=None):
    p = argparse.ArgumentParser(prog="zero_to_fp32")
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("--tag", default=None)
    args = p.parse_args(argv)
    sd = convert_zero_checkpoint_to_fp32_state_dict(
        args.checkpoint_dir, args.output_file, args.tag)
    total = sum(int(np.prod(v.shape)) for v in sd.values())
    print(f"wrote {len(sd)} fp32 tensors ({total:,} params) "
          f"to {args.output_file}")


if __name__ == "__main__":
    main()
