"""Long-sequence benchmark: dense flash vs block-sparse layout-skip kernel.

The reference's block-sparse claim (10x longer sequences,
docs/_pages/training.md:108) rests on attention cost scaling with layout
density. This sweep measures wall-clock per forward at growing seq length for
dense flash_attention vs block_sparse_flash_attention with a sliding-window +
global layout, on the real chip: `python -m
deepspeed_tpu.benchmarks.sparse_attention_bench [--seqs 4096,8192,16384]`.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.pallas.block_sparse_attention import block_sparse_flash_attention
from ..ops.pallas.flash_attention import flash_attention
from ..ops.sparse_attention import BSLongformerSparsityConfig


def _timed(attn_fn, q, k, v, iters=20):
    """Per-call latency with the loop INSIDE one compiled program: host->chip
    RPC (hundreds of us..ms on tunneled setups) would otherwise swamp the
    kernel. Each iteration depends on the last so nothing is elided; the
    marginal cost comes from differencing two loop lengths."""
    import jax.lax as lax

    def many(n):
        def run(q, k, v):
            def body(i, carry):
                qq = q.at[0, 0, 0, 0].add(carry.astype(q.dtype))
                o = attn_fn(qq, k, v)
                return o[0, 0, 0, 0].astype(jnp.float32)
            return lax.fori_loop(0, n, body, jnp.zeros((), jnp.float32))
        f = jax.jit(run)
        np.asarray(f(q, k, v))              # compile + warm; fetch = fence
        t0 = time.perf_counter()
        np.asarray(f(q, k, v))              # value fetch forces completion
        return time.perf_counter() - t0

    t_long = many(iters)
    t_short = many(iters // 4)
    return (t_long - t_short) / (iters - iters // 4)


def run(seqs, heads=8, head_dim=128, block=128, window_blocks=5):
    rows = []
    for S in seqs:
        rng = np.random.default_rng(0)
        shape = (1, heads, S, head_dim)
        q, k, v = (jnp.asarray(rng.standard_normal(shape), jnp.bfloat16)
                   for _ in range(3))
        cfg = BSLongformerSparsityConfig(
            num_heads=heads, block=block,
            num_sliding_window_blocks=window_blocks)
        layout = cfg.make_layout(S)
        density = float(layout.mean())

        t_d = _timed(lambda q, k, v: flash_attention(q, k, v, causal=False),
                     q, k, v)
        t_s = _timed(lambda q, k, v: block_sparse_flash_attention(
            q, k, v, layout, block, causal=False), q, k, v)
        rows.append({"seq": S, "density": round(density, 4),
                     "dense_ms": round(t_d * 1e3, 3),
                     "sparse_ms": round(t_s * 1e3, 3),
                     "speedup": round(t_d / t_s, 2)})
        print(rows[-1])
    return rows


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--seqs", default="4096,8192,16384")
    args = p.parse_args(argv)
    run([int(s) for s in args.seqs.split(",")])


if __name__ == "__main__":
    main()
