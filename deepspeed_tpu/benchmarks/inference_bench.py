"""Inference latency benchmarks — prefill/forward + generation sweeps,
plus a Poisson-arrival serving-load leg.

Capability parity with the reference's ``benchmarks/inference`` (bert/gpt
latency scripts): measures forward latency over batch/seq and per-token
decode latency with the KV-cache generate loop, on the current backend.
``--poisson`` drives the round-8 continuous-batching serving loop
(deepspeed_tpu/serving/) with open-loop Poisson arrivals at fixed request
rates, reporting tokens/s/chip and p50/p99 request latency — the
serving-SLO counterpart of the closed-loop sweeps above, with a
machine-readable ``inference_bench poisson: {json}`` line in the PR-7
dryrun-timings style. ``--poisson --fleet N`` (round 11) drives the
supervised N-replica fleet instead and injects a replica kill mid-run,
printing a ``poisson_fleet`` row with tokens/s before/during/after the
loss — the serving tier's resilience number.

Round 12 adds the newest-recorded-sweep regression convention (the
COMMBENCH / dryrun-timings pattern): ``--record PATH`` writes the
serving rows as JSON (commit as ``SERVEBENCH_rNN.json``), every
``--poisson`` run compares its rows against the newest recorded sweep in
``--baseline-dir`` (same device count), >2x p50 latency or <1/2 the
recorded tokens/s prints a LOUD regression, and
``DSTPU_SERVE_BENCH_GATE=1`` makes it fatal. ``--chunk N`` arms chunked
prefill for the serving rows (mode column records it).

Round 18 adds the process-placement leg: ``--fleet N --placement
process`` drives the process-per-replica fleet (serving/procfleet.py —
worker processes over the transfer fabric) and SIGKILLs a replica
PROCESS at 1/3 completion, printing a ``poisson_fleet_proc`` row with
tokens/s before/during/after the real process death; the row's
``heartbeat_dir`` is live for ``dstpu health`` (per-process replica
rows with pid/queue/pool gauges).

Round 17 adds the quantized-compute legs: ``--kv-dtype int8`` serves
from the int8 KV pool (in-kernel dequant) and ``--weight-dtype int8``
from blockwise weight-only int8 matmuls; the rows carry ``kv_dtype`` /
``weight_dtype`` columns and the regression key includes them, so the
bf16 and int8 tiers baseline independently.

    python -m deepspeed_tpu.benchmarks.inference_bench \
        [--preset gpt2-125m] [--batches 1,8] [--seqs 128,1024] [--new 64]
    python -m deepspeed_tpu.benchmarks.inference_bench --poisson \
        [--rates 2,8] [--requests 64] [--prompt 128] [--new 64] \
        [--fleet 3] [--no-fail-replica] [--slow-replica [--slow-ms 250]] \
        [--chunk 0] [--record PATH]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: >2x recorded p50 (or < recorded tokens/s / 2) = loud regression
SERVE_REGRESSION_FACTOR = 2.0


def _fence(out):
    # fetch ONE element: forces execution without the full D2H (axon's
    # block_until_ready does not fence — see benchmarks/sparse_attention_bench)
    leaf = jax.tree.leaves(out)[0]
    return np.asarray(leaf.reshape(-1)[0])


def _timed(fn, iters=5):
    _fence(fn())                         # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        _fence(fn())
    return (time.perf_counter() - t0) / iters


def run(preset: str, batches: List[int], seqs: List[int], new_tokens: int):
    from ..models import build_model
    from ..models.generation import generate
    rows = []
    for B in batches:
        for S in seqs:
            model, cfg = build_model(preset, max_seq_len=S + new_tokens)
            ids = jnp.asarray(np.random.default_rng(0).integers(
                0, cfg.vocab_size, (B, S)))
            # per-(B,S) sweep point builds a new model: a fresh trace per
            # point is inherent to the sweep
            # graftlint: disable=TPU002
            params = jax.jit(lambda r: model.init(r, {"input_ids": ids})
                             ["params"])(jax.random.PRNGKey(0))
            # graftlint: disable=TPU002
            fwd = jax.jit(lambda p, i: model.apply({"params": p},
                                                   {"input_ids": i}))
            t_fwd = _timed(lambda: fwd(params, ids))
            t_gen = _timed(
                lambda: generate(cfg, params, ids, new_tokens), iters=3)
            per_tok = (t_gen - t_fwd) / new_tokens
            rows.append({
                "preset": preset, "batch": B, "seq": S,
                "forward_ms": round(t_fwd * 1e3, 2),
                "generate_ms": round(t_gen * 1e3, 2),
                "ms_per_token": round(per_tok * 1e3, 3),
                "tokens_per_sec": round(B / max(per_tok, 1e-9), 1)})
            print(rows[-1])
    return rows


def run_ragged(preset: str, batch: int, max_seq: int, new_tokens: int):
    """Batched serving with MIXED context lengths: one left-padded ragged
    batch (per-sample positions/masks) vs the sum of per-sample runs —
    the batching win the round-3 decode bench (B=1 only) never measured."""
    from ..models import build_model
    from ..models.generation import generate
    model, cfg = build_model(preset, max_seq_len=max_seq + new_tokens)
    rng = np.random.default_rng(0)
    lens = [int(x) for x in
            rng.integers(max_seq // 4, max_seq + 1, size=batch)]
    ids = np.zeros((batch, max_seq), np.int64)
    mask = np.zeros((batch, max_seq), np.int64)
    for i, L in enumerate(lens):
        ids[i, max_seq - L:] = rng.integers(1, cfg.vocab_size, size=L)
        mask[i, max_seq - L:] = 1
    ids_j, mask_j = jnp.asarray(ids), jnp.asarray(mask)
    # one-shot bench setup: init compiles once before the timed region
    # graftlint: disable=TPU002
    params = jax.jit(lambda r: model.init(r, {"input_ids": ids_j})
                     ["params"])(jax.random.PRNGKey(0))
    t_batch = _timed(lambda: generate(cfg, params, ids_j, new_tokens,
                                      attention_mask=mask_j), iters=3)
    t_seq = 0.0
    probe = lens[:4]                       # sample of per-sample runs
    for i, L in enumerate(probe):
        one = jnp.asarray(ids[i, max_seq - L:][None])
        t_seq += _timed(lambda: generate(cfg, params, one, new_tokens),
                        iters=3)
    t_seq *= batch / len(probe)            # extrapolate to full batch
    row = {"preset": preset, "batch": batch, "ctx_lens": lens,
           "new_tokens": new_tokens,
           "ragged_batch_s": round(t_batch, 3),
           "sequential_est_s": round(t_seq, 3),
           "batching_speedup": round(t_seq / max(t_batch, 1e-9), 2),
           "tokens_per_sec": round(batch * new_tokens / t_batch, 1)}
    print(row)
    return row


def run_poisson(preset: str, rate: float, num_requests: int,
                prompt_len: int, new_tokens: int,
                serving: Optional[dict] = None, seed: int = 0,
                model_kwargs: Optional[dict] = None) -> dict:
    """Open-loop Poisson load against the continuous-batching serving loop.

    Requests arrive at exponential inter-arrival times (rate = requests/s)
    regardless of server progress — the open-loop regime where queueing
    delay shows up honestly (a closed loop would self-throttle). Reports
    per-request latency (arrival -> completion, so queue wait counts)
    p50/p99 and steady-state tokens/s/chip, plus the machine-readable
    line the regression tooling greps::

        inference_bench poisson: {"rate": 8.0, "p50_s": ..., ...}
    """
    from ..models import build_model
    from ..serving.engine import ServingEngine
    model, cfg = build_model(preset, max_seq_len=prompt_len + new_tokens,
                             **(model_kwargs or {}))
    rng = np.random.default_rng(seed)
    ids0 = rng.integers(0, cfg.vocab_size, (1, prompt_len))
    # one-shot bench setup: init compiles once before the timed region
    # graftlint: disable=TPU002
    params = jax.jit(lambda r: model.init(r, {"input_ids": ids0})
                     ["params"])(jax.random.PRNGKey(0))
    eng = ServingEngine(cfg, params, serving=serving)

    # a shared "system prompt" prefix (2 blocks) exercises the prefix
    # cache the way production traffic does; suffixes vary per request
    shared = 2 * eng.block_size
    sys_prompt = rng.integers(1, cfg.vocab_size, size=min(shared,
                                                          prompt_len // 2))
    prompts = []
    for _ in range(num_requests):
        suffix_len = max(1, prompt_len - len(sys_prompt))
        prompts.append(list(sys_prompt)
                       + list(rng.integers(1, cfg.vocab_size,
                                           size=suffix_len)))
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=num_requests))

    # warm the compile caches outside the timed window (serving latency,
    # not XLA latency, is measured): warm A compiles the FULL-prompt
    # prefill bucket and seeds the prefix cache; warm B, sharing the
    # system prompt, takes the prefix hit and compiles the SUFFIX bucket
    # every timed request will actually use — plus the one decode step
    def _mk_prompt():
        suffix_len = max(1, prompt_len - len(sys_prompt))
        return (list(sys_prompt)
                + list(rng.integers(1, cfg.vocab_size, size=suffix_len)))
    for _ in range(2):
        warm = eng.submit(_mk_prompt(), 2)
        eng.run_until_idle()
        assert warm.done

    reqs = []
    lat: List[float] = []
    t0 = time.perf_counter()
    next_i = 0
    while len(lat) < num_requests:
        now = time.perf_counter() - t0
        while next_i < num_requests and arrivals[next_i] <= now:
            i = next_i
            reqs.append((eng.submit(prompts[i], new_tokens), arrivals[i]))
            next_i += 1
        if eng.idle:
            if next_i < num_requests:
                time.sleep(max(arrivals[next_i] - (time.perf_counter() - t0),
                               0.0))
            continue
        eng.step()
        done_now = time.perf_counter() - t0
        still = []
        for req, arr in reqs:
            if req.done:
                lat.append(done_now - arr)
            else:
                still.append((req, arr))
        reqs = still
    wall = time.perf_counter() - t0
    n_chips = jax.device_count()
    gen_tokens = num_requests * new_tokens
    row = {
        "mode": "poisson",
        "preset": preset, "rate": float(rate), "requests": num_requests,
        "prompt": prompt_len, "new_tokens": new_tokens,
        "chunk": int((serving or {}).get("prefill_chunk_tokens", 0)),
        "kv_dtype": (serving or {}).get("kv_cache_dtype"),
        "weight_dtype": (serving or {}).get("weight_dtype"),
        "wall_s": round(wall, 3),
        "p50_s": round(float(np.percentile(lat, 50)), 4),
        "p99_s": round(float(np.percentile(lat, 99)), 4),
        "mean_s": round(float(np.mean(lat)), 4),
        "tokens_per_s": round(gen_tokens / wall, 1),
        "tokens_per_s_per_chip": round(gen_tokens / wall / n_chips, 1),
        "prefix_hit_tokens": eng.stats["prefix_hit_tokens"],
        "n_chips": n_chips,
    }
    eng.close()      # loop exit stamps EXIT if a heartbeat is attached
    print("inference_bench poisson: " + json.dumps(row))
    return row


def run_poisson_fleet(preset: str, rate: float, num_requests: int,
                      prompt_len: int, new_tokens: int, replicas: int = 2,
                      serving: Optional[dict] = None,
                      fail_replica: bool = True, seed: int = 0,
                      slow_replica: bool = False, slow_ms: int = 250,
                      model_kwargs: Optional[dict] = None) -> dict:
    """Poisson load against the supervised multi-replica fleet
    (serving/fleet.py), with an optional failure-injection leg: once a
    third of the requests have completed, ``serve.replica_kill`` takes
    out the last replica mid-decode, and the row records tokens/s
    BEFORE / DURING / AFTER the loss — the resilience number ROADMAP
    item 1(c) asks the first serving BENCH entry to carry. "during"
    spans kill -> requeue-complete (detection + teardown + requeue +
    replay); "after" is the recovered fleet. Machine-readable row::

        inference_bench poisson_fleet: {"rate": ..., "replicas": ...,
            "tps_before": ..., "tps_during": ..., "tps_after": ...,
            "requeues": ..., "deaths": ..., ...}

    ``slow_replica`` (round 15, the straggler defense) injects a
    DEGRADED replica instead of a dead one: the keyed
    ``serve.replica_slow`` failpoint sleeps ``slow_ms`` per worker
    iteration (times=0, forever) so the victim keeps serving — slowly —
    until the FleetSupervisor's relative-slowness detector DRAINS it
    (requeue + warmed restart). The row's mode is
    ``poisson_fleet_slow``; ``drained_at_s`` is the detection instant
    and ``recovered_at_s`` the warmed restart, so the degraded window
    tokens/s is directly readable."""
    from ..models import build_model
    from ..serving.fleet import ServingFleet
    from ..testing import chaos
    model, cfg = build_model(preset, max_seq_len=prompt_len + new_tokens,
                             **(model_kwargs or {}))
    rng = np.random.default_rng(seed)
    ids0 = rng.integers(0, cfg.vocab_size, (1, prompt_len))
    # one-shot bench setup: init compiles once before the timed region
    # graftlint: disable=TPU002
    params = jax.jit(lambda r: model.init(r, {"input_ids": ids0})
                     ["params"])(jax.random.PRNGKey(0))
    scfg = dict(serving or {})
    fleet_cfg = dict(scfg.pop("fleet", {}))
    fleet_cfg.setdefault("replicas", replicas)
    # snappy recovery for the bench window (production defaults are lazier)
    fleet_cfg.setdefault("poll_interval", 0.05)
    fleet_cfg.setdefault("heartbeat_interval", 0.05)
    if slow_replica:
        # the drain needs the detector on. Windows run at poll cadence,
        # so consecutive windows are CORRELATED samples of the same
        # rolling gauge — strike_window must be wide enough to span a
        # gauge turnover, and rel_threshold generous: in-process
        # replicas on a shared host are anti-correlated by construction
        # (one replica's step starves the other), which is noise a
        # chip-per-replica deployment doesn't have
        fleet_cfg.setdefault("straggler", {
            "enabled": True, "warmup": 3, "strike_window": 4,
            "cooldown": 20, "rel_threshold": 2.5})
        # the SILENCE detector must not race the straggler drain: a
        # degraded replica still stamps (slowly), and on a starved bench
        # host the default 10s would flap healthy replicas long before
        # the relative detector earns its verdict
        fleet_cfg.setdefault("heartbeat_timeout", 300.0)
        # both replicas must actually CARRY work for relative detection
        # to mean anything: with the default 8 lanes one replica can
        # swallow a whole small bench run at admission
        scfg.setdefault("max_batch", 2)
    scfg["fleet"] = fleet_cfg
    flt = ServingFleet(cfg, params, serving=scfg)
    flt.start()

    # warm EVERY replica's compile caches outside the timed window (each
    # engine has its own jit closures; a cold replica would bill XLA
    # latency to the serving numbers)
    flt.warmup(prompt=list(rng.integers(1, cfg.vocab_size,
                                        size=prompt_len)))
    base = dict(flt.stats)              # row reports the timed window only

    prompts = [list(rng.integers(1, cfg.vocab_size, size=prompt_len))
               for _ in range(num_requests)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=num_requests))
    t0 = time.perf_counter()
    t0_mono = time.monotonic()
    reqs: List = []
    next_i = 0
    killed_at = None
    kill_target = str(int(fleet_cfg["replicas"]) - 1)
    timeline: List[tuple] = []          # (t, tokens_emitted) samples
    while True:
        now = time.perf_counter() - t0
        while next_i < num_requests and arrivals[next_i] <= now:
            reqs.append(flt.submit(prompts[next_i], new_tokens))
            next_i += 1
        done = sum(1 for r in reqs if r.done)
        timeline.append((now, flt.stats["tokens_emitted"]))
        # the slow leg additionally waits for the victim to HOLD lanes:
        # slowing an idle replica degrades nothing and detects nothing
        victim_busy = (not slow_replica
                       or bool(flt._replicas[int(kill_target)].inflight))
        if ((fail_replica or slow_replica) and killed_at is None
                and victim_busy
                and done >= max(num_requests // 3, 1)):
            if slow_replica:
                # degraded, not dead: the victim keeps serving at
                # sleep-inflated step times until the straggler drain
                chaos.arm("serve.replica_slow", "sleep", ms=int(slow_ms),
                          times=0, match=kill_target)
            else:
                chaos.arm("serve.replica_kill", "raise", match=kill_target)
            killed_at = now
        if (slow_replica and killed_at is not None
                and flt.stats["deaths"] > base["deaths"]
                and chaos.armed()):
            # drained: lift the injection so the warmed replacement
            # rejoins at full speed (the recovery the row measures)
            chaos.disarm("serve.replica_slow")
        if next_i >= num_requests and done >= num_requests:
            break
        time.sleep(0.005)
    wall = time.perf_counter() - t0
    if killed_at is not None:
        # the victim may have died with no in-flight work, in which case
        # the drain above never waited on detection — give the supervisor
        # its poll so the row's death/attribution columns are stable
        t_wait = time.perf_counter()
        while (flt.stats["deaths"] == base["deaths"]
               and time.perf_counter() - t_wait < 10.0):
            time.sleep(0.01)
    chaos.disarm("serve.replica_kill")
    chaos.disarm("serve.replica_slow")

    def _tps(t_lo, t_hi):
        if t_hi - t_lo <= 0:
            return None
        lo = min((s for s in timeline if s[0] >= t_lo),
                 default=timeline[-1])
        hi = max((s for s in timeline if s[0] <= t_hi),
                 default=timeline[-1])
        if hi[0] - lo[0] <= 0:
            return None
        return round((hi[1] - lo[1]) / (hi[0] - lo[0]), 1)

    # recovery instant: the death ledger's restart stamp, in bench time
    # (for the slow leg also the DRAIN instant — detection, before the
    # warmed restart — so the degraded window is directly readable)
    t_rec = t_drain = None
    if flt.deaths:
        rts = flt.deaths[-1]["restarted_ts"] or flt.deaths[-1]["detected_ts"]
        t_rec = rts - t0_mono
        t_drain = flt.deaths[-1]["detected_ts"] - t0_mono
    lat = sorted(r.finish_ts - (t0_mono + arr)
                 for r, arr in zip(reqs, arrivals) if r.finish_ts)
    n_chips = jax.device_count()
    mode = "poisson_fleet_slow" if slow_replica else "poisson_fleet"
    row = {
        "mode": mode,
        "preset": preset, "rate": float(rate), "replicas":
            int(fleet_cfg["replicas"]), "requests": num_requests,
        "prompt": prompt_len, "new_tokens": new_tokens,
        "chunk": int(scfg.get("prefill_chunk_tokens", 0)),
        "kv_dtype": scfg.get("kv_cache_dtype"),
        "weight_dtype": scfg.get("weight_dtype"),
        "wall_s": round(wall, 3),
        "p50_s": round(float(np.percentile(lat, 50)), 4),
        "p99_s": round(float(np.percentile(lat, 99)), 4),
        "tokens_per_s": round(num_requests * new_tokens / wall, 1),
        "tokens_per_s_per_chip": round(
            num_requests * new_tokens / wall / n_chips, 1),
        "tps_before": _tps(0.0, killed_at) if killed_at else None,
        "tps_during": (_tps(killed_at, t_rec)
                       if killed_at and t_rec else None),
        "tps_after": _tps(t_rec, wall) if t_rec else None,
        "kill_at_s": (round(killed_at, 3)
                      if killed_at and not slow_replica else None),
        "slow_at_s": (round(killed_at, 3)
                      if killed_at and slow_replica else None),
        "drained_at_s": (round(t_drain, 3)
                         if slow_replica and t_drain else None),
        "recovered_at_s": round(t_rec, 3) if t_rec else None,
        "deaths": flt.stats["deaths"] - base["deaths"],
        "requeues": flt.stats["requeues"] - base["requeues"],
        "completed": flt.stats["completed"] - base["completed"],
        "failed": flt.stats["failed"] - base["failed"],
        "timeout": flt.stats["timeout"] - base["timeout"],
        "n_chips": n_chips,
    }
    flt.close()
    print(f"inference_bench {mode}: " + json.dumps(row))
    return row


def run_poisson_fleet_proc(preset: str, rate: float, num_requests: int,
                           prompt_len: int, new_tokens: int,
                           replicas: int = 2,
                           serving: Optional[dict] = None,
                           fail_replica: bool = True, seed: int = 0,
                           model_kwargs: Optional[dict] = None) -> dict:
    """Poisson load against the PROCESS-placement fleet (round 18,
    serving/procfleet.py): each replica engine in a supervised OS
    process, request/token streams over the transfer fabric's TCP star.
    Once a third of the requests have completed, the last replica's
    PROCESS takes a real ``SIGKILL`` — actual process death, not a
    failpoint — and the row records tokens/s BEFORE / DURING / AFTER
    the loss plus the death-ledger columns, the process-placement
    counterpart of the ``poisson_fleet`` resilience number. The
    heartbeat channel is a real directory (``heartbeat_dir`` column):
    ``dstpu health <dir>`` shows the per-process replica rows —
    pid/queue/pool gauges per worker — mid-run and after. Row::

        inference_bench poisson_fleet_proc: {"rate": ..., "replicas":
            ..., "tps_before": ..., "tps_during": ..., "tps_after": ...,
            "requeues": ..., "deaths": ..., ...}
    """
    import signal as _signal

    from ..models import build_model
    from ..serving.procfleet import ProcessFleet
    model, cfg = build_model(preset, max_seq_len=prompt_len + new_tokens,
                             **(model_kwargs or {}))
    rng = np.random.default_rng(seed)
    ids0 = rng.integers(0, cfg.vocab_size, (1, prompt_len))
    # one-shot bench setup: init compiles once before the timed region
    # graftlint: disable=TPU002
    params = jax.jit(lambda r: model.init(r, {"input_ids": ids0})
                     ["params"])(jax.random.PRNGKey(0))
    scfg = dict(serving or {})
    fleet_cfg = dict(scfg.pop("fleet", {}))
    fleet_cfg.setdefault("replicas", replicas)
    fleet_cfg["placement"] = "process"
    # snappy recovery for the bench window (production defaults are lazier)
    fleet_cfg.setdefault("poll_interval", 0.05)
    fleet_cfg.setdefault("heartbeat_interval", 0.05)
    scfg["fleet"] = fleet_cfg
    flt = ProcessFleet(cfg, params, serving=scfg)
    flt.start()
    # workers warm THEMSELVES at spawn (weights + compile off the
    # serving path); this is the ready barrier, not the trigger
    flt.warmup(timeout=600.0)
    base = dict(flt.stats)              # row reports the timed window only

    prompts = [list(rng.integers(1, cfg.vocab_size, size=prompt_len))
               for _ in range(num_requests)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=num_requests))
    t0 = time.perf_counter()
    t0_mono = time.monotonic()
    reqs: List = []
    next_i = 0
    killed_at = None
    victim = int(fleet_cfg["replicas"]) - 1
    timeline: List[tuple] = []          # (t, tokens_emitted) samples
    while True:
        now = time.perf_counter() - t0
        while next_i < num_requests and arrivals[next_i] <= now:
            reqs.append(flt.submit(prompts[next_i], new_tokens))
            next_i += 1
        done = sum(1 for r in reqs if r.done)
        timeline.append((now, flt.stats["tokens_emitted"]))
        if (fail_replica and killed_at is None
                and done >= max(num_requests // 3, 1)):
            pid = flt.pids().get(victim)
            if pid is not None:
                os.kill(pid, _signal.SIGKILL)   # a real process death
                killed_at = now
        if next_i >= num_requests and done >= num_requests:
            break
        time.sleep(0.005)
    wall = time.perf_counter() - t0
    if killed_at is not None:
        # the victim may have died idle — give the supervisor its poll
        # so the row's death/attribution columns are stable
        t_wait = time.perf_counter()
        while (flt.stats["deaths"] == base["deaths"]
               and time.perf_counter() - t_wait < 10.0):
            time.sleep(0.01)

    def _tps(t_lo, t_hi):
        if t_hi - t_lo <= 0:
            return None
        lo = min((s for s in timeline if s[0] >= t_lo),
                 default=timeline[-1])
        hi = max((s for s in timeline if s[0] <= t_hi),
                 default=timeline[-1])
        if hi[0] - lo[0] <= 0:
            return None
        return round((hi[1] - lo[1]) / (hi[0] - lo[0]), 1)

    t_rec = None
    if flt.deaths:
        rts = (flt.deaths[-1]["restarted_ts"]
               or flt.deaths[-1]["detected_ts"])
        t_rec = rts - t0_mono
    lat = sorted(r.finish_ts - (t0_mono + arr)
                 for r, arr in zip(reqs, arrivals) if r.finish_ts)
    n_chips = jax.device_count()
    row = {
        "mode": "poisson_fleet_proc",
        "preset": preset, "rate": float(rate),
        "replicas": int(fleet_cfg["replicas"]), "requests": num_requests,
        "prompt": prompt_len, "new_tokens": new_tokens,
        "chunk": int(scfg.get("prefill_chunk_tokens", 0)),
        "kv_dtype": scfg.get("kv_cache_dtype"),
        "weight_dtype": scfg.get("weight_dtype"),
        "wall_s": round(wall, 3),
        "p50_s": round(float(np.percentile(lat, 50)), 4),
        "p99_s": round(float(np.percentile(lat, 99)), 4),
        "tokens_per_s": round(num_requests * new_tokens / wall, 1),
        "tokens_per_s_per_chip": round(
            num_requests * new_tokens / wall / n_chips, 1),
        "tps_before": _tps(0.0, killed_at) if killed_at else None,
        "tps_during": (_tps(killed_at, t_rec)
                       if killed_at and t_rec else None),
        "tps_after": _tps(t_rec, wall) if t_rec else None,
        "kill_at_s": round(killed_at, 3) if killed_at else None,
        "recovered_at_s": round(t_rec, 3) if t_rec else None,
        "deaths": flt.stats["deaths"] - base["deaths"],
        "requeues": flt.stats["requeues"] - base["requeues"],
        "completed": flt.stats["completed"] - base["completed"],
        "failed": flt.stats["failed"] - base["failed"],
        "timeout": flt.stats["timeout"] - base["timeout"],
        "heartbeat_dir": flt.heartbeat_dir,
        "n_chips": n_chips,
    }
    flt.close()
    print("inference_bench poisson_fleet_proc: " + json.dumps(row))
    return row


def parse_trace(spec: str) -> List[Tuple[float, float]]:
    """``--trace`` spec -> [(rate_req_per_s, duration_s), ...]. The
    format is comma-separated ``rate@seconds`` segments, e.g.
    ``0.5@10,1.5@10,0.5@10`` — a 3x burst framed by the base rate —
    driven open-loop as piecewise-Poisson arrivals."""
    segs = []
    for part in spec.split(","):
        rate, dur = part.split("@")
        segs.append((float(rate), float(dur)))
    if not segs:
        raise ValueError(f"--trace {spec!r}: no segments")
    return segs


def trace_arrivals(segs: List[Tuple[float, float]], rng) -> List[float]:
    """Piecewise-Poisson arrival times over the trace segments."""
    arrivals, start = [], 0.0
    for rate, dur in segs:
        t, end = start, start + dur
        while True:
            t += float(rng.exponential(1.0 / rate))
            if t >= end:
                break
            arrivals.append(t)
        start = end
    return arrivals


#: deterministic tier mix for the autoscale leg: mostly standard, a
#: latency request (tight-deadline SLO traffic) and a batch request
#: (deferrable backfill) interleaved — enough of each for per-tier p99
_TIER_CYCLE = ("standard", "latency", "standard", "batch", "standard")


def run_poisson_autoscale(preset: str, trace: List[Tuple[float, float]],
                          prompt_len: int, new_tokens: int,
                          serving: Optional[dict] = None, seed: int = 0,
                          max_replicas: int = 3,
                          model_kwargs: Optional[dict] = None) -> dict:
    """Bursty piecewise-Poisson load against the AUTOSCALING fleet
    (round 19): the fleet starts at ``min_replicas=1``, the trace's
    burst segment pushes queue depth over the scale-up trigger, the
    supervisor spawns warmed replicas up to ``max_replicas``, and the
    post-burst idle trough drains them back down. Requests carry mixed
    priority tiers (``_TIER_CYCLE``), so the row reports per-tier p99 —
    the traffic-shaping number: latency-tier p99 should survive the
    burst that batch-tier p99 absorbs. Machine-readable row::

        inference_bench poisson_autoscale: {"trace": "...", "scale_ups":
            ..., "scale_downs": ..., "p99_by_tier": {...}, ...}

    ``clean_drain`` asserts the conclusion: every request concluded,
    every scale-down's drain completed (``drained_ts`` stamped), and
    the fleet ended back at its floor."""
    from ..models import build_model
    from ..serving.fleet import ServingFleet
    model, cfg = build_model(preset, max_seq_len=prompt_len + new_tokens,
                             **(model_kwargs or {}))
    rng = np.random.default_rng(seed)
    ids0 = rng.integers(0, cfg.vocab_size, (1, prompt_len))
    # one-shot bench setup: init compiles once before the timed region
    # graftlint: disable=TPU002
    params = jax.jit(lambda r: model.init(r, {"input_ids": ids0})
                     ["params"])(jax.random.PRNGKey(0))
    scfg = dict(serving or {})
    fleet_cfg = dict(scfg.pop("fleet", {}))
    fleet_cfg.setdefault("replicas", 1)
    fleet_cfg.setdefault("poll_interval", 0.05)
    fleet_cfg.setdefault("heartbeat_interval", 0.05)
    # a warm scale-up compile on CPU can starve sibling heartbeats for
    # tens of seconds (GIL-bound tracing) — the bench measures traffic
    # shaping, not silence detection (run_poisson_fleet's convention)
    fleet_cfg.setdefault("heartbeat_timeout", 300.0)
    # aging short enough that a queued batch request can still promote
    # within the bench window (the starvation floor, observable)
    fleet_cfg.setdefault("priority_aging_s", 30.0)
    fleet_cfg.setdefault("autoscale", {
        "enabled": True, "min_replicas": 1, "max_replicas": max_replicas,
        "up_queue_per_replica": 2, "up_after": 2,
        "down_idle_s": 1.0, "cooldown_s": 2.0})
    scfg["fleet"] = fleet_cfg
    flt = ServingFleet(cfg, params, serving=scfg)
    flt.start()
    flt.warmup(prompt=list(rng.integers(1, cfg.vocab_size,
                                        size=prompt_len)))
    base = dict(flt.stats)

    arrivals = trace_arrivals(trace, rng)
    n = len(arrivals)
    prompts = [list(rng.integers(1, cfg.vocab_size, size=prompt_len))
               for _ in range(n)]
    tiers = [_TIER_CYCLE[i % len(_TIER_CYCLE)] for i in range(n)]
    trace_end = sum(d for _, d in trace)
    t0 = time.perf_counter()
    t0_mono = time.monotonic()
    reqs: List = []
    next_i = 0
    max_live = len(flt.live_replicas())
    while True:
        now = time.perf_counter() - t0
        while next_i < n and arrivals[next_i] <= now:
            reqs.append(flt.submit(
                prompts[next_i], new_tokens, priority=tiers[next_i]))
            next_i += 1
        max_live = max(max_live, len(flt.live_replicas()))
        if next_i >= n and all(r.done for r in reqs):
            break
        time.sleep(0.005)
    wall = time.perf_counter() - t0
    # the idle tail: give the trough trigger its down_idle_s + cooldown
    # so the row records the drain-down, not just the spawn-up
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        ups = sum(1 for e in flt.scale_events if e.action == "up")
        downs = [e for e in flt.scale_events if e.action == "down"]
        if ups and downs and all(e.drained_ts for e in downs) \
                and len(flt.live_replicas()) <= max(
                    1, int(fleet_cfg["autoscale"]["min_replicas"])):
            break
        time.sleep(0.05)

    lat_by_tier: Dict[str, List[float]] = {}
    for r, arr in zip(reqs, arrivals):
        if r.finish_ts:
            lat_by_tier.setdefault(r.priority, []).append(
                r.finish_ts - (t0_mono + arr))
    p99 = {t: round(float(np.percentile(v, 99)), 4)
           for t, v in sorted(lat_by_tier.items())}
    downs = [e for e in flt.scale_events if e.action == "down"]
    clean_drain = (all(r.done for r in reqs)
                   and all(e.drained_ts is not None for e in downs))
    n_chips = jax.device_count()
    row = {
        "mode": "poisson_autoscale",
        "preset": preset,
        "trace": ",".join(f"{r:g}@{d:g}" for r, d in trace),
        "rate": trace[0][0],            # regression key: the base rate
        "burst_rate": max(r for r, _ in trace),
        "requests": n, "prompt": prompt_len, "new_tokens": new_tokens,
        "trace_s": round(trace_end, 1), "wall_s": round(wall, 3),
        "p50_s": round(float(np.percentile(
            [v for vs in lat_by_tier.values() for v in vs], 50)), 4),
        "p99_s": round(float(np.percentile(
            [v for vs in lat_by_tier.values() for v in vs], 99)), 4),
        "p99_by_tier": p99,
        "tokens_per_s": round(n * new_tokens / wall, 1),
        "replicas_floor": int(fleet_cfg["replicas"]),
        "max_replicas": max_replicas, "max_live": max_live,
        "scale_ups": flt.stats["scale_ups"] - base["scale_ups"],
        "scale_downs": flt.stats["scale_downs"] - base["scale_downs"],
        "scale_events": [
            {"action": e.action, "replica": e.replica,
             "reason": e.reason, "t_s": round(e.ts - t0_mono, 3),
             "drained_t_s": (round(e.drained_ts - t0_mono, 3)
                             if e.drained_ts else None)}
            for e in flt.scale_events],
        "shed": flt.stats["shed"] - base["shed"],
        "preempted": flt.stats["preempted"] - base["preempted"],
        "completed": flt.stats["completed"] - base["completed"],
        "failed": flt.stats["failed"] - base["failed"],
        "timeout": flt.stats["timeout"] - base["timeout"],
        "clean_drain": bool(clean_drain),
        "n_chips": n_chips,
    }
    flt.close()
    print("inference_bench poisson_autoscale: " + json.dumps(row))
    return row


def record_serve_bench(rows: List[Dict], path: str) -> str:
    """Write serving-bench rows in the SERVEBENCH report shape (the
    comm-sweep convention: ``{"n": device_count, "rows": [...]}`` so
    baselines from a different topology are skipped)."""
    doc = {"n": jax.device_count(), "rows": rows}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"inference_bench: recorded {len(rows)} serving rows -> {path}")
    return path


def latest_serve_bench(baseline_dir: str, n_devices: Optional[int] = None
                       ) -> Tuple[Optional[str], List[Dict]]:
    """(name, rows) of the newest recorded serving sweep in
    ``baseline_dir`` (``SERVEBENCH_r*.json`` reports or
    ``serve_bench*.json`` recordings); sweeps from a different device
    count are skipped — their throughputs aren't comparable."""
    from .sweeps import latest_recorded_sweep
    return latest_recorded_sweep(
        baseline_dir, ("SERVEBENCH_r*.json", "serve_bench*.json"),
        n_devices)


def check_serve_regression(current: List[Dict], baseline: List[Dict],
                           factor: float = SERVE_REGRESSION_FACTOR
                           ) -> List[str]:
    """Rows whose p50 latency exceeds ``factor`` x the recorded one, or
    whose tokens/s fell below recorded / ``factor`` — keyed by
    (mode, preset, rate, prompt, new_tokens, replicas, chunk, kv_dtype,
    weight_dtype) so the round-17 quantized legs never gate the bf16 row
    (or vice versa). Missing rows are NOT flagged (a narrower re-run is
    legitimate)."""
    def key(r):
        return (r.get("mode", "poisson"), r.get("preset"),
                r.get("rate"), r.get("prompt"), r.get("new_tokens"),
                r.get("replicas"), r.get("chunk", 0),
                r.get("kv_dtype"), r.get("weight_dtype"))

    base = {key(r): r for r in baseline}
    problems = []
    for r in current:
        b = base.get(key(r))
        if b is None:
            continue
        p50, bp50 = r.get("p50_s"), b.get("p50_s")
        if p50 and bp50 and float(p50) > factor * float(bp50):
            problems.append(
                f"{r.get('mode')}@rate={r.get('rate')}: p50 {p50:.3f}s vs "
                f"recorded {bp50:.3f}s ({p50 / bp50:.1f}x > {factor:g}x)")
        tps, btps = r.get("tokens_per_s"), b.get("tokens_per_s")
        if tps and btps and float(tps) < float(btps) / factor:
            problems.append(
                f"{r.get('mode')}@rate={r.get('rate')}: tokens/s {tps:.1f} "
                f"vs recorded {btps:.1f} (<1/{factor:g})")
    return problems


def run_spatial(size: int, batch: int, channels: int = 64,
                context_len: int = 77):
    """Conditional-UNet forward latency (the diffusion serving hot loop —
    the reference's diffusers injection slot)."""
    from ..inference import InferenceEngine
    from ..inference.spatial import UNet2DCondition
    unet = UNet2DCondition(block_channels=(channels, 2 * channels),
                           num_heads=8, out_channels=4, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, size, size, 4)), jnp.bfloat16)
    t = jnp.ones((batch,), jnp.float32)
    ctx = jnp.asarray(rng.normal(size=(batch, context_len, 2 * channels)),
                      jnp.bfloat16)
    # one-shot bench setup: init compiles once before the timed region
    # graftlint: disable=TPU002
    params = jax.jit(lambda r: unet.init(r, x, t, ctx)["params"])(
        jax.random.PRNGKey(0))
    eng = InferenceEngine(model=unet, model_parameters=params,
                          config={"dtype": "bfloat16"})
    dt = _timed(lambda: eng.forward(x, t, ctx))
    row = {"model": "unet2d-cond", "latent": size, "batch": batch,
           "channels": channels, "forward_ms": round(dt * 1e3, 2),
           "images_per_s": round(batch / dt, 2)}
    print(row)
    return row


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="gpt2-125m")
    p.add_argument("--batches", default="1,8")
    p.add_argument("--seqs", default="128,1024")
    p.add_argument("--new", type=int, default=64)
    p.add_argument("--ragged", action="store_true",
                   help="mixed-context left-padded batch bench")
    p.add_argument("--ragged-batch", type=int, default=8)
    p.add_argument("--ragged-seq", type=int, default=512)
    p.add_argument("--spatial", action="store_true",
                   help="conditional-UNet forward latency")
    p.add_argument("--latent", type=int, default=64)
    p.add_argument("--poisson", action="store_true",
                   help="Poisson-arrival load vs the serving loop")
    p.add_argument("--rates", default="2,8",
                   help="request rates (req/s), comma-separated")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--prompt", type=int, default=128)
    p.add_argument("--fleet", type=int, default=0,
                   help="with --poisson: drive a supervised N-replica "
                        "fleet instead of one engine; prints the "
                        "poisson_fleet degraded-throughput row")
    p.add_argument("--placement", choices=("thread", "process"),
                   default="thread",
                   help="fleet leg replica placement: 'process' (round "
                        "18) runs each replica in a supervised OS "
                        "process over the transfer fabric and SIGKILLs "
                        "a replica PROCESS at 1/3 completion — the "
                        "poisson_fleet_proc degraded-throughput row")
    p.add_argument("--no-fail-replica", action="store_true",
                   help="fleet leg: skip the replica-kill injection "
                        "(steady-state fleet throughput only)")
    p.add_argument("--slow-replica", action="store_true",
                   help="fleet leg: inject a DEGRADED (not dead) replica "
                        "via the keyed serve.replica_slow sleep failpoint "
                        "at 1/3 completion; the straggler detector drains "
                        "it and the poisson_fleet_slow row records "
                        "tps_before/during/after + drain/recovery stamps")
    p.add_argument("--slow-ms", type=int, default=250,
                   help="--slow-replica: injected per-iteration delay")
    p.add_argument("--trace", default="",
                   help="with --poisson: bursty piecewise-Poisson trace "
                        "as rate@seconds segments (e.g. 0.5@10,1.5@10,"
                        "0.5@10 — a 3x burst) against the AUTOSCALING "
                        "fleet with mixed priority tiers; prints the "
                        "poisson_autoscale row (scale events, per-tier "
                        "p99, clean drain)")
    p.add_argument("--max-replicas", type=int, default=3,
                   help="--trace: autoscaler ceiling (floor is 1)")
    p.add_argument("--chunk", type=int, default=0,
                   help="serving.prefill_chunk_tokens for the poisson "
                        "legs (0 = whole prefill)")
    p.add_argument("--kv-dtype", choices=("int8", "bf16", "f32"),
                   default=None,
                   help="serving.kv_cache_dtype for the poisson legs "
                        "(int8 = quantized pool, in-kernel dequant; "
                        "default: model dtype)")
    p.add_argument("--weight-dtype", choices=("int8",), default=None,
                   help="serving.weight_dtype for the poisson legs "
                        "(int8 = blockwise weight-only quant, packed "
                        "once at engine build)")
    p.add_argument("--record", default="",
                   help="write the poisson rows to this JSON path "
                        "(commit as SERVEBENCH_rNN.json)")
    p.add_argument("--baseline-dir", default=".",
                   help="directory searched for the newest recorded "
                        "serving sweep to compare against (>2x p50 or "
                        "<1/2 tokens/s = loud regression; "
                        "DSTPU_SERVE_BENCH_GATE=1 makes it fatal)")
    args = p.parse_args(argv)
    if args.spatial:
        run_spatial(args.latent, int(args.batches.split(",")[0]))
        return
    if args.ragged:
        run_ragged(args.preset, args.ragged_batch, args.ragged_seq, args.new)
        return
    if args.poisson:
        serving = {}
        if args.chunk > 0:
            serving["prefill_chunk_tokens"] = args.chunk
        if args.kv_dtype:
            serving["kv_cache_dtype"] = args.kv_dtype
        if args.weight_dtype:
            serving["weight_dtype"] = args.weight_dtype
        serving = serving or None
        rows = []
        if args.trace:
            rows.append(run_poisson_autoscale(
                args.preset, parse_trace(args.trace), args.prompt,
                args.new, serving=serving,
                max_replicas=args.max_replicas))
        for rate in ((float(x) for x in args.rates.split(","))
                     if not args.trace else ()):
            if args.fleet > 1 and args.placement == "process":
                rows.append(run_poisson_fleet_proc(
                    args.preset, rate, args.requests, args.prompt,
                    args.new, replicas=args.fleet, serving=serving,
                    fail_replica=not args.no_fail_replica))
            elif args.fleet > 1:
                rows.append(run_poisson_fleet(
                    args.preset, rate, args.requests, args.prompt,
                    args.new, replicas=args.fleet, serving=serving,
                    fail_replica=(not args.no_fail_replica
                                  and not args.slow_replica),
                    slow_replica=args.slow_replica, slow_ms=args.slow_ms))
            else:
                rows.append(run_poisson(args.preset, rate, args.requests,
                                        args.prompt, args.new,
                                        serving=serving))
        base_name, baseline = latest_serve_bench(args.baseline_dir,
                                                 jax.device_count())
        problems = (check_serve_regression(rows, baseline)
                    if baseline else [])
        if problems:
            msg = (f"SERVING REGRESSION vs {base_name}:\n  "
                   + "\n  ".join(problems))
            if os.environ.get("DSTPU_SERVE_BENCH_GATE") == "1":
                raise SystemExit(msg)
            print(msg)
        elif base_name:
            print(f"inference_bench: no serving regression vs {base_name}")
        if args.record:
            record_serve_bench(rows, args.record)
        return
    run(args.preset, [int(x) for x in args.batches.split(",")],
        [int(x) for x in args.seqs.split(",")], args.new)


if __name__ == "__main__":
    main()
