"""Inference latency benchmarks — prefill/forward + generation sweeps.

Capability parity with the reference's ``benchmarks/inference`` (bert/gpt
latency scripts): measures forward latency over batch/seq and per-token
decode latency with the KV-cache generate loop, on the current backend.

    python -m deepspeed_tpu.benchmarks.inference_bench \
        [--preset gpt2-125m] [--batches 1,8] [--seqs 128,1024] [--new 64]
"""

from __future__ import annotations

import argparse
import time
from typing import List

import jax
import jax.numpy as jnp
import numpy as np


def _fence(out):
    # fetch ONE element: forces execution without the full D2H (axon's
    # block_until_ready does not fence — see benchmarks/sparse_attention_bench)
    leaf = jax.tree.leaves(out)[0]
    return np.asarray(leaf.reshape(-1)[0])


def _timed(fn, iters=5):
    _fence(fn())                         # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        _fence(fn())
    return (time.perf_counter() - t0) / iters


def run(preset: str, batches: List[int], seqs: List[int], new_tokens: int):
    from ..models import build_model
    from ..models.generation import generate
    rows = []
    for B in batches:
        for S in seqs:
            model, cfg = build_model(preset, max_seq_len=S + new_tokens)
            ids = jnp.asarray(np.random.default_rng(0).integers(
                0, cfg.vocab_size, (B, S)))
            params = jax.jit(lambda r: model.init(r, {"input_ids": ids})
                             ["params"])(jax.random.PRNGKey(0))
            fwd = jax.jit(lambda p, i: model.apply({"params": p},
                                                   {"input_ids": i}))
            t_fwd = _timed(lambda: fwd(params, ids))
            t_gen = _timed(
                lambda: generate(cfg, params, ids, new_tokens), iters=3)
            per_tok = (t_gen - t_fwd) / new_tokens
            rows.append({
                "preset": preset, "batch": B, "seq": S,
                "forward_ms": round(t_fwd * 1e3, 2),
                "generate_ms": round(t_gen * 1e3, 2),
                "ms_per_token": round(per_tok * 1e3, 3),
                "tokens_per_sec": round(B / max(per_tok, 1e-9), 1)})
            print(rows[-1])
    return rows


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="gpt2-125m")
    p.add_argument("--batches", default="1,8")
    p.add_argument("--seqs", default="128,1024")
    p.add_argument("--new", type=int, default=64)
    args = p.parse_args(argv)
    run(args.preset, [int(x) for x in args.batches.split(",")],
        [int(x) for x in args.seqs.split(",")], args.new)


if __name__ == "__main__":
    main()
