"""Collective benchmarks — latency / algorithm BW / bus BW sweeps.

Capability parity with the reference's ``benchmarks/communication/*`` +
``bin/ds_bench`` (all_reduce/all_gather/all_to_all/broadcast/pt2pt sweeps
with algbw/busbw accounting). TPU edition: collectives run inside shard_map
over the full device mesh; busbw factors follow the standard ring-algorithm
accounting the reference uses (all_reduce busbw = 2(n-1)/n * algbw, etc.).
"""

from __future__ import annotations

import argparse
import time
from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _mesh_all():
    devs = jax.devices()
    return Mesh(np.asarray(devs), ("all",))


def _timed(fn, arg, iters: int, warmups: int = 2) -> float:
    for _ in range(warmups):
        out = fn(arg)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(arg)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _collective_fn(op: str, mesh) -> Callable:
    n = mesh.devices.size

    if op == "all_reduce":
        return jax.jit(jax.shard_map(
            lambda x: jax.lax.psum(x, "all"),
            mesh=mesh, in_specs=P("all"), out_specs=P("all"), check_vma=False))
    if op == "all_gather":
        return jax.jit(jax.shard_map(
            lambda x: jax.lax.all_gather(x, "all", tiled=True),
            mesh=mesh, in_specs=P("all"), out_specs=P(), check_vma=False))
    if op == "reduce_scatter":
        return jax.jit(jax.shard_map(
            lambda x: jax.lax.psum_scatter(x, "all", tiled=True),
            mesh=mesh, in_specs=P(), out_specs=P("all"), check_vma=False))
    if op == "all_to_all":
        return jax.jit(jax.shard_map(
            lambda x: jax.lax.all_to_all(
                x.reshape(n, -1), "all", split_axis=0, concat_axis=0,
                tiled=True).reshape(-1),
            mesh=mesh, in_specs=P("all"), out_specs=P("all"), check_vma=False))
    if op == "pt2pt":
        perm = [(i, (i + 1) % n) for i in range(n)]
        return jax.jit(jax.shard_map(
            lambda x: jax.lax.ppermute(x, "all", perm),
            mesh=mesh, in_specs=P("all"), out_specs=P("all"), check_vma=False))
    raise ValueError(f"unknown op {op}")


def busbw_factor(op: str, n: int) -> float:
    """Ring-algorithm bus bandwidth factors (reference: communication/utils.py)."""
    if n <= 1:
        return 1.0
    return {
        "all_reduce": 2.0 * (n - 1) / n,
        "all_gather": (n - 1) / n,
        "reduce_scatter": (n - 1) / n,
        "all_to_all": (n - 1) / n,
        "pt2pt": 1.0,
    }[op]


def run_op_sweep(op: str, sizes_mb: List[float], dtype=jnp.bfloat16,
                 iters: int = 10) -> List[Dict]:
    mesh = _mesh_all()
    n = mesh.devices.size
    fn = _collective_fn(op, mesh)
    itemsize = jnp.dtype(dtype).itemsize
    rows = []
    # reduce_scatter consumes a per-rank FULL buffer (in_specs=P()), so place
    # the input replicated; sharding it P('all') would fold an implicit
    # all-gather into the timed region and corrupt the measurement
    in_spec = P() if op == "reduce_scatter" else P("all")
    for mb in sizes_mb:
        numel = max(int(mb * 2 ** 20 / itemsize) // n * n, n)
        x = jax.device_put(jnp.ones((numel,), dtype),
                           NamedSharding(mesh, in_spec))
        dt = _timed(fn, x, iters)
        size_bytes = numel * itemsize
        algbw = size_bytes / dt / 1e9
        rows.append({"op": op, "size_mb": round(size_bytes / 2 ** 20, 3),
                     "latency_us": round(dt * 1e6, 1),
                     "algbw_gbps": round(algbw, 3),
                     "busbw_gbps": round(algbw * busbw_factor(op, n), 3)})
    return rows


def print_table(rows: List[Dict]):
    if not rows:
        return
    cols = list(rows[0])
    widths = [max(len(c), max(len(str(r[c])) for r in rows)) for c in cols]
    line = "  ".join(c.ljust(w) for c, w in zip(cols, widths))
    print(line)
    print("-" * len(line))
    for r in rows:
        print("  ".join(str(r[c]).ljust(w) for c, w in zip(cols, widths)))


def main(argv=None):
    p = argparse.ArgumentParser(prog="ds_bench",
                                description="collective benchmark sweeps")
    p.add_argument("--ops", default="all_reduce,all_gather,reduce_scatter,"
                                    "all_to_all,pt2pt")
    p.add_argument("--sizes-mb", default="1,16,64")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args(argv)
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
             "float16": jnp.float16}[args.dtype]
    sizes = [float(s) for s in args.sizes_mb.split(",")]
    all_rows = []
    for op in args.ops.split(","):
        all_rows += run_op_sweep(op.strip(), sizes, dtype, args.iters)
    print_table(all_rows)


if __name__ == "__main__":
    main()
