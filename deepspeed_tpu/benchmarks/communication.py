"""Collective benchmarks — latency / algorithm BW / bus BW sweeps.

Capability parity with the reference's ``benchmarks/communication/*`` +
``bin/ds_bench`` (all_reduce/all_gather/all_to_all/broadcast/pt2pt sweeps
with algbw/busbw accounting). TPU edition: collectives run inside shard_map
over the full device mesh; busbw factors follow the standard ring-algorithm
accounting the reference uses (all_reduce busbw = 2(n-1)/n * algbw, etc.).

Round 10 additions (the comm-plan subsystem's measurement source):

* ``--algos`` sweeps WIRE FORMATS per op — ``exact`` plus the quantized
  implementations (``int8`` for all_reduce / reduce_scatter / all_to_all
  via ``runtime/comm``, ``onebit`` for all_reduce) — so the selector has
  real measurements to choose from;
* every row is ALSO printed as a machine-readable ``comm_bench: {json}``
  line (the format ``comm_plan.selector.parse_bench_lines`` ingests);
* ``--record PATH`` writes the sweep as JSON, and each run compares its
  rows against the newest recorded sweep next to it with the same >2x
  loud-regression convention as the dryrun timing gate
  (``DSTPU_COMM_BENCH_GATE=1`` makes a regression fatal).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map

#: wire formats each op can sweep (exact always; quantized/overlap where
#: an implementation exists in runtime/comm). The overlap family times a
#: PIPELINE (chunked collective interleaved with a matmul payload) and
#: records its EXPOSED comm time as latency_us — wall minus compute —
#: so the selector compares it against exact's pure-wire latency on
#: equal terms; the raw wall/compute/comm split and the overlap_ratio
#: (wall / sum-of-parts; < 1 means the schedule actually hid wire time)
#: ride the row for the humans.
OP_ALGOS = {
    "all_reduce": ("exact", "int8", "onebit"),
    "all_gather": ("exact", "overlap", "overlap_int8"),
    "reduce_scatter": ("exact", "int8", "overlap", "overlap_int8"),
    "all_to_all": ("exact", "int8"),
    "pt2pt": ("exact",),
}

OVERLAP_ALGOS = ("overlap", "overlap_int8")

#: chunk count the benchmark's overlap cells use (the engine's is
#: comm_plan.overlap_chunks; rows record theirs in the "chunks" field)
OVERLAP_CHUNKS = 4

#: a row slower than this factor vs the newest recorded sweep is loud
SWEEP_REGRESSION_FACTOR = 2.0


def _mesh_all():
    devs = jax.devices()
    return Mesh(np.asarray(devs), ("all",))


def build_mesh(spec: str):
    """``'data=2,model=4'`` -> a named mesh over the first prod(sizes)
    devices (the per-axis sweep's substrate: one row per mesh axis, so
    hierarchical ICI/DCN selection has real per-axis measurements);
    ``''`` -> the flat ``('all',)`` mesh."""
    if not spec:
        return _mesh_all()
    names, sizes = [], []
    for part in spec.split(","):
        name, _, size = part.strip().partition("=")
        if not name or not size:
            raise ValueError(f"--mesh entry {part!r}: expected name=size")
        names.append(name)
        sizes.append(int(size))
    total = int(np.prod(sizes))
    devs = jax.devices()
    if total > len(devs):
        raise ValueError(f"--mesh {spec!r} needs {total} devices; "
                         f"host has {len(devs)}")
    return Mesh(np.asarray(devs[:total]).reshape(sizes), tuple(names))


def sweep_axes(mesh) -> List[str]:
    """The axes a sweep records rows for: every mesh axis of size > 1
    (a single-member axis has no wire to measure)."""
    return [a for a in mesh.axis_names if mesh.shape[a] > 1] or \
        [mesh.axis_names[0]]


def _timed(fn, arg, iters: int, warmups: int = 2) -> float:
    for _ in range(warmups):
        out = fn(arg)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(arg)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _collective_fn(op: str, mesh, axis: str = "all") -> Callable:
    n = mesh.shape[axis]
    manual = {axis}

    if op == "all_reduce":
        return jax.jit(shard_map(
            lambda x: jax.lax.psum(x, axis),
            mesh=mesh, in_specs=P(axis), out_specs=P(axis),
            axis_names=manual, check_vma=False))
    if op == "all_gather":
        return jax.jit(shard_map(
            lambda x: jax.lax.all_gather(x, axis, tiled=True),
            mesh=mesh, in_specs=P(axis), out_specs=P(),
            axis_names=manual, check_vma=False))
    if op == "reduce_scatter":
        return jax.jit(shard_map(
            lambda x: jax.lax.psum_scatter(x, axis, tiled=True),
            mesh=mesh, in_specs=P(), out_specs=P(axis),
            axis_names=manual, check_vma=False))
    if op == "all_to_all":
        return jax.jit(shard_map(
            lambda x: jax.lax.all_to_all(
                x.reshape(n, -1), axis, split_axis=0, concat_axis=0,
                tiled=True).reshape(-1),
            mesh=mesh, in_specs=P(axis), out_specs=P(axis),
            axis_names=manual, check_vma=False))
    if op == "pt2pt":
        perm = [(i, (i + 1) % n) for i in range(n)]
        return jax.jit(shard_map(
            lambda x: jax.lax.ppermute(x, axis, perm),
            mesh=mesh, in_specs=P(axis), out_specs=P(axis),
            axis_names=manual, check_vma=False))
    raise ValueError(f"unknown op {op}")


def _quantized_setup(op: str, algo: str, mesh, numel: int, dtype,
                     axis: str = "all") -> Tuple[Callable, jnp.ndarray]:
    """(fn, input) for a quantized wire format. ``numel`` is the same
    total element count the exact cell ran; each op maps it onto the
    stacked per-rank layout its runtime/comm collective consumes so the
    PER-RANK payload matches the exact variant's (allreduce family:
    per-rank value numel/n like the exact shard; reduce_scatter: each
    rank contributes a FULL numel buffer like the exact replicated
    input; all_to_all: numel/n sent per rank like the exact local
    (n, numel/n^2) chunking) — latency rows stay apples-to-apples."""
    from ..runtime.comm.compressed import (chunk_elems, compressed_allreduce,
                                           quantized_allreduce)
    from ..runtime.comm.quantized import (quantized_all_to_all,
                                          quantized_reduce_scatter)
    n = mesh.shape[axis]
    sh = NamedSharding(mesh, P(axis))
    per_rank = numel // n
    # one OUTER jit per cell so the timing loop hits the compile cache
    # (the runtime/comm collectives build their shard_map per trace —
    # correct under a caller's jit, a retrace per call when timed bare)
    if op == "all_reduce" and algo == "int8":
        x = jax.device_put(jnp.ones((n, per_rank), dtype), sh)
        err = jax.device_put(jnp.zeros((n, per_rank), jnp.float32), sh)
        return (jax.jit(lambda v: quantized_allreduce(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
            v, err, mesh=mesh, axis=axis)[0]), x)
    if op == "all_reduce" and algo == "onebit":
        x = jax.device_put(jnp.ones((n, per_rank), dtype), sh)
        werr = jax.device_put(jnp.zeros((n, per_rank), jnp.float32), sh)
        serr = jax.device_put(
            jnp.zeros((n, chunk_elems(per_rank, n)), jnp.float32), sh)
        return (jax.jit(lambda v: compressed_allreduce(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
            v, werr, serr, mesh=mesh, axis=axis)[0]), x)
    if op == "reduce_scatter" and algo == "int8":
        # each rank contributes a FULL buffer, like the exact replicated input
        x = jax.device_put(jnp.ones((n, numel), dtype), sh)
        return (jax.jit(lambda v: quantized_reduce_scatter(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
            v, mesh=mesh, axis=axis)), x)
    if op == "all_to_all" and algo == "int8":
        rows = n * n
        # logical [n*n, numel/n^2]: numel/n sent per rank, matching the
        # exact cell's local (n, numel/n^2) chunking
        x = jax.device_put(jnp.ones((rows, max(numel // rows, 1)), dtype),
                           sh)
        return (jax.jit(lambda v: quantized_all_to_all(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
            v, mesh=mesh, axis=axis)), x)
    raise ValueError(f"no {algo!r} implementation for op {op!r}")


def _overlap_setup(op: str, algo: str, mesh, numel: int, dtype,
                   axis: str = "all", chunks: int = OVERLAP_CHUNKS):
    """(wall_fn, wall_arg, comm_fn, comm_arg, compute_fn, compute_arg)
    for an overlap cell: the fused chunked pipeline, its comm-only half
    (same chunked collectives, compute precomputed) and its compute-only
    half (same matmul payload, wire precomputed). ``latency_us`` is the
    EXPOSED comm (wall - compute); per-rank wire payload matches the
    exact cell (all_gather: the shard each rank contributes;
    reduce_scatter: a full per-rank buffer)."""
    from ..runtime.comm.overlap import (chunked_ag_matmul, chunked_matmul_rs,
                                        chunked_rs, make_overlap_gather)
    n = mesh.shape[axis]
    sh = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())
    B = 64                                   # matmul payload's free dim
    if op == "all_gather":
        # w [R, C] sharded on dim 0 (each rank contributes numel/n, like
        # the exact cell's shard), consumed chunk-by-chunk by x @ w
        C = max(min(512, numel // (n * chunks)), 1)
        R = max(numel // C // (n * chunks), 1) * n * chunks
        w = jax.device_put(jnp.ones((R, C), dtype),
                           NamedSharding(mesh, P(axis)))
        x = jax.device_put(jnp.ones((B, R), dtype), rep)
        wfull = jax.device_put(jnp.ones((R, C), dtype), rep)
        gather = make_overlap_gather(mesh, axis, 0, chunks=chunks,
                                     algo=algo)
        return (jax.jit(lambda v: chunked_ag_matmul(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
                    x, v, mesh=mesh, axis=axis, chunks=chunks, algo=algo)),
                w,
                jax.jit(gather), w,  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
                jax.jit(lambda wf: x.astype(jnp.float32)  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
                        @ wf.astype(jnp.float32)), wfull)
    if op == "reduce_scatter":
        # each rank PRODUCES a full numel buffer chunk-by-chunk (u @ v
        # segments) and reduce-scatters each chunk as it appears
        u = jax.device_put(jnp.ones((n, B), dtype), sh)
        v = jax.device_put(jnp.ones((B, numel), dtype), rep)
        g = jax.device_put(jnp.ones((n, numel), dtype), sh)
        return (jax.jit(lambda vv: chunked_matmul_rs(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
                    u, vv, mesh=mesh, axis=axis, chunks=chunks, algo=algo)),
                v,
                jax.jit(lambda gg: chunked_rs(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
                    gg, mesh=mesh, axis=axis, chunks=chunks, algo=algo)),
                g,
                jax.jit(lambda vv: u[:1].astype(jnp.float32)  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
                        @ vv.astype(jnp.float32)), v)
    raise ValueError(f"no {algo!r} implementation for op {op!r}")


def busbw_factor(op: str, n: int) -> float:
    """Ring-algorithm bus bandwidth factors (reference: communication/utils.py)."""
    if n <= 1:
        return 1.0
    return {
        "all_reduce": 2.0 * (n - 1) / n,
        "all_gather": (n - 1) / n,
        "reduce_scatter": (n - 1) / n,
        "all_to_all": (n - 1) / n,
        "pt2pt": 1.0,
    }[op]


def run_op_sweep(op: str, sizes_mb: List[float], dtype=jnp.bfloat16,
                 iters: int = 10, algo: str = "exact",
                 emit: bool = False, mesh=None,
                 axis: Optional[str] = None) -> List[Dict]:
    mesh = _mesh_all() if mesh is None else mesh
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    itemsize = jnp.dtype(dtype).itemsize
    rows = []
    # reduce_scatter consumes a per-rank FULL buffer (in_specs=P()), so place
    # the input replicated; sharding it over the swept axis would fold an
    # implicit all-gather into the timed region and corrupt the measurement
    in_spec = P() if op == "reduce_scatter" else P(axis)
    fn = _collective_fn(op, mesh, axis) if algo == "exact" else None
    for mb in sizes_mb:
        base = max(int(mb * 2 ** 20 / itemsize) // n * n, n)
        numel = -(-base // (n * n)) * n * n      # divisible for every layout
        size_bytes = numel * itemsize
        row = {"op": op, "algo": algo, "axis": axis, "n": n,
               "size_mb": round(size_bytes / 2 ** 20, 3),
               "size_bytes": size_bytes}
        if algo in OVERLAP_ALGOS:
            (wall_fn, wall_x, comm_fn, comm_x,
             compute_fn, compute_x) = _overlap_setup(op, algo, mesh, numel,
                                                     dtype, axis)
            wall = _timed(wall_fn, wall_x, iters)
            comm = _timed(comm_fn, comm_x, iters)
            compute = _timed(compute_fn, compute_x, iters)
            dt = max(wall - compute, 1e-7)       # exposed comm time
            row.update({
                "latency_us": round(dt * 1e6, 1),
                "wall_us": round(wall * 1e6, 1),
                "comm_us": round(comm * 1e6, 1),
                "compute_us": round(compute * 1e6, 1),
                "overlap_ratio": round(wall / max(comm + compute, 1e-12),
                                       3),
                "chunks": OVERLAP_CHUNKS,
            })
        else:
            if algo == "exact":
                x = jax.device_put(jnp.ones((numel,), dtype),
                                   NamedSharding(mesh, in_spec))
                timed_fn = fn
            else:
                timed_fn, x = _quantized_setup(op, algo, mesh, numel,
                                               dtype, axis)
            dt = _timed(timed_fn, x, iters)
            row["latency_us"] = round(dt * 1e6, 1)
        algbw = size_bytes / dt / 1e9
        row["algbw_gbps"] = round(algbw, 3)
        row["busbw_gbps"] = round(algbw * busbw_factor(op, n), 3)
        rows.append(row)
        if emit:
            print("comm_bench: " + json.dumps(row))
    return rows


def print_table(rows: List[Dict]):
    if not rows:
        return
    cols = []                       # union of keys, first-seen order
    for r in rows:                  # (overlap rows carry extra columns)
        for c in r:
            if c not in cols:
                cols.append(c)
    widths = [max(len(c), max(len(str(r.get(c, ""))) for r in rows))
              for c in cols]
    line = "  ".join(c.ljust(w) for c, w in zip(cols, widths))
    print(line)
    print("-" * len(line))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(w)
                        for c, w in zip(cols, widths)))


# ---------------------------------------------------------------------------
# recorded sweeps + regression compare (dryrun timing-gate convention)
# ---------------------------------------------------------------------------

def record_sweep(rows: List[Dict], path: str) -> str:
    doc = {"n": rows[0]["n"] if rows else len(jax.devices()), "rows": rows}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, path)
    return path


def latest_comm_sweep(baseline_dir: str, n_devices: Optional[int] = None
                      ) -> Tuple[Optional[str], List[Dict]]:
    """(name, rows) of the newest recorded sweep in ``baseline_dir``
    (``COMMBENCH_r*.json`` reports or ``comm_sweep*.json`` recordings);
    sweeps from a different device count are skipped — their latencies
    aren't comparable."""
    from .sweeps import latest_recorded_sweep
    return latest_recorded_sweep(
        baseline_dir, ("COMMBENCH_r*.json", "comm_sweep*.json"), n_devices)


def check_sweep_regression(current: List[Dict], baseline: List[Dict],
                           factor: float = SWEEP_REGRESSION_FACTOR
                           ) -> List[str]:
    """Rows > ``factor`` x their recorded latency, keyed by
    (op, algo, axis, size_mb). Missing rows are NOT flagged (a narrower
    re-run is legitimate; the dryrun gate owns leg-coverage)."""
    def key(r):
        return (r.get("op"), r.get("algo", "exact"), r.get("axis", "all"),
                r.get("size_mb"))

    base = {key(r): float(r["latency_us"]) for r in baseline
            if "latency_us" in r}
    problems = []
    for r in current:
        b = base.get(key(r))
        if b is None or b <= 0 or "latency_us" not in r:
            continue
        now = float(r["latency_us"])
        if now > factor * b:
            problems.append(
                f"{r['op']}/{r.get('algo', 'exact')}@{r.get('size_mb')}MB: "
                f"{now:.1f}us vs recorded {b:.1f}us "
                f"({now / b:.1f}x > {factor:g}x budget)")
    return problems


def main(argv=None):
    p = argparse.ArgumentParser(prog="ds_bench",
                                description="collective benchmark sweeps")
    p.add_argument("--ops", default="all_reduce,all_gather,reduce_scatter,"
                                    "all_to_all,pt2pt")
    p.add_argument("--algos", default="exact",
                   help="comma list of wire formats per op "
                        "(exact,int8,onebit); unsupported (op, algo) "
                        "pairs are skipped")
    p.add_argument("--sizes-mb", default="1,16,64")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--mesh", default="",
                   help="named mesh spec 'data=2,model=4': one sweep row "
                        "per >1-member axis (per-axis plans for "
                        "hierarchical meshes); empty = the flat 'all' "
                        "mesh")
    p.add_argument("--record", default="",
                   help="write the sweep rows to this JSON path (the "
                        "comm-plan selector's input)")
    p.add_argument("--baseline-dir", default=".",
                   help="directory searched for the newest recorded "
                        "sweep to compare against (>2x = loud "
                        "regression; DSTPU_COMM_BENCH_GATE=1 makes it "
                        "fatal)")
    args = p.parse_args(argv)
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
             "float16": jnp.float16}[args.dtype]
    sizes = [float(s) for s in args.sizes_mb.split(",")]
    algos = [a.strip() for a in args.algos.split(",") if a.strip()]
    mesh = build_mesh(args.mesh)
    all_rows = []
    for op in args.ops.split(","):
        op = op.strip()
        for algo in algos:
            if algo not in OP_ALGOS.get(op, ()):
                continue
            for axis in sweep_axes(mesh):
                all_rows += run_op_sweep(op, sizes, dtype, args.iters,
                                         algo=algo, emit=True,
                                         mesh=mesh, axis=axis)
    print_table(all_rows)
    base_name, baseline = latest_comm_sweep(args.baseline_dir,
                                            len(jax.devices()))
    if baseline:
        problems = check_sweep_regression(all_rows, baseline)
        for prob in problems:
            print(f"comm_bench REGRESSION vs {base_name}: {prob}")
        if not problems:
            print(f"comm_bench within {SWEEP_REGRESSION_FACTOR:g}x of "
                  f"{base_name}")
        elif os.environ.get("DSTPU_COMM_BENCH_GATE") == "1":
            raise SystemExit("comm_bench regression:\n" +
                             "\n".join(problems))
    if args.record:
        print(f"comm_bench recorded: {record_sweep(all_rows, args.record)}")


if __name__ == "__main__":
    main()
