"""Collective benchmarks — latency / algorithm BW / bus BW sweeps.

Capability parity with the reference's ``benchmarks/communication/*`` +
``bin/ds_bench`` (all_reduce/all_gather/all_to_all/broadcast/pt2pt sweeps
with algbw/busbw accounting). TPU edition: collectives run inside shard_map
over the full device mesh; busbw factors follow the standard ring-algorithm
accounting the reference uses (all_reduce busbw = 2(n-1)/n * algbw, etc.).

Round 10 additions (the comm-plan subsystem's measurement source):

* ``--algos`` sweeps WIRE FORMATS per op — ``exact`` plus the quantized
  implementations (``int8`` for all_reduce / reduce_scatter / all_to_all
  via ``runtime/comm``, ``onebit`` for all_reduce) — so the selector has
  real measurements to choose from;
* every row is ALSO printed as a machine-readable ``comm_bench: {json}``
  line (the format ``comm_plan.selector.parse_bench_lines`` ingests);
* ``--record PATH`` writes the sweep as JSON, and each run compares its
  rows against the newest recorded sweep next to it with the same >2x
  loud-regression convention as the dryrun timing gate
  (``DSTPU_COMM_BENCH_GATE=1`` makes a regression fatal).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map

#: wire formats each op can sweep (exact always; quantized where an
#: implementation exists in runtime/comm)
OP_ALGOS = {
    "all_reduce": ("exact", "int8", "onebit"),
    "all_gather": ("exact",),
    "reduce_scatter": ("exact", "int8"),
    "all_to_all": ("exact", "int8"),
    "pt2pt": ("exact",),
}

#: a row slower than this factor vs the newest recorded sweep is loud
SWEEP_REGRESSION_FACTOR = 2.0


def _mesh_all():
    devs = jax.devices()
    return Mesh(np.asarray(devs), ("all",))


def _timed(fn, arg, iters: int, warmups: int = 2) -> float:
    for _ in range(warmups):
        out = fn(arg)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(arg)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _collective_fn(op: str, mesh) -> Callable:
    n = mesh.devices.size

    if op == "all_reduce":
        return jax.jit(shard_map(
            lambda x: jax.lax.psum(x, "all"),
            mesh=mesh, in_specs=P("all"), out_specs=P("all"), check_vma=False))
    if op == "all_gather":
        return jax.jit(shard_map(
            lambda x: jax.lax.all_gather(x, "all", tiled=True),
            mesh=mesh, in_specs=P("all"), out_specs=P(), check_vma=False))
    if op == "reduce_scatter":
        return jax.jit(shard_map(
            lambda x: jax.lax.psum_scatter(x, "all", tiled=True),
            mesh=mesh, in_specs=P(), out_specs=P("all"), check_vma=False))
    if op == "all_to_all":
        return jax.jit(shard_map(
            lambda x: jax.lax.all_to_all(
                x.reshape(n, -1), "all", split_axis=0, concat_axis=0,
                tiled=True).reshape(-1),
            mesh=mesh, in_specs=P("all"), out_specs=P("all"), check_vma=False))
    if op == "pt2pt":
        perm = [(i, (i + 1) % n) for i in range(n)]
        return jax.jit(shard_map(
            lambda x: jax.lax.ppermute(x, "all", perm),
            mesh=mesh, in_specs=P("all"), out_specs=P("all"), check_vma=False))
    raise ValueError(f"unknown op {op}")


def _quantized_setup(op: str, algo: str, mesh, numel: int, dtype
                     ) -> Tuple[Callable, jnp.ndarray]:
    """(fn, input) for a quantized wire format. ``numel`` is the same
    total element count the exact cell ran; each op maps it onto the
    stacked per-rank layout its runtime/comm collective consumes so the
    PER-RANK payload matches the exact variant's (allreduce family:
    per-rank value numel/n like the exact shard; reduce_scatter: each
    rank contributes a FULL numel buffer like the exact replicated
    input; all_to_all: numel/n sent per rank like the exact local
    (n, numel/n^2) chunking) — latency rows stay apples-to-apples."""
    from ..runtime.comm.compressed import (chunk_elems, compressed_allreduce,
                                           quantized_allreduce)
    from ..runtime.comm.quantized import (quantized_all_to_all,
                                          quantized_reduce_scatter)
    n = mesh.devices.size
    sh = NamedSharding(mesh, P("all"))
    per_rank = numel // n
    # one OUTER jit per cell so the timing loop hits the compile cache
    # (the runtime/comm collectives build their shard_map per trace —
    # correct under a caller's jit, a retrace per call when timed bare)
    if op == "all_reduce" and algo == "int8":
        x = jax.device_put(jnp.ones((n, per_rank), dtype), sh)
        err = jax.device_put(jnp.zeros((n, per_rank), jnp.float32), sh)
        return (jax.jit(lambda v: quantized_allreduce(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
            v, err, mesh=mesh, axis="all")[0]), x)
    if op == "all_reduce" and algo == "onebit":
        x = jax.device_put(jnp.ones((n, per_rank), dtype), sh)
        werr = jax.device_put(jnp.zeros((n, per_rank), jnp.float32), sh)
        serr = jax.device_put(
            jnp.zeros((n, chunk_elems(per_rank, n)), jnp.float32), sh)
        return (jax.jit(lambda v: compressed_allreduce(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
            v, werr, serr, mesh=mesh, axis="all")[0]), x)
    if op == "reduce_scatter" and algo == "int8":
        # each rank contributes a FULL buffer, like the exact replicated input
        x = jax.device_put(jnp.ones((n, numel), dtype), sh)
        return (jax.jit(lambda v: quantized_reduce_scatter(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
            v, mesh=mesh, axis="all")), x)
    if op == "all_to_all" and algo == "int8":
        rows = n * n
        # logical [n*n, numel/n^2]: numel/n sent per rank, matching the
        # exact cell's local (n, numel/n^2) chunking
        x = jax.device_put(jnp.ones((rows, max(numel // rows, 1)), dtype),
                           sh)
        return (jax.jit(lambda v: quantized_all_to_all(  # graftlint: disable=TPU002 (one jit per sweep cell, reused across timed iters)
            v, mesh=mesh, axis="all")), x)
    raise ValueError(f"no {algo!r} implementation for op {op!r}")


def busbw_factor(op: str, n: int) -> float:
    """Ring-algorithm bus bandwidth factors (reference: communication/utils.py)."""
    if n <= 1:
        return 1.0
    return {
        "all_reduce": 2.0 * (n - 1) / n,
        "all_gather": (n - 1) / n,
        "reduce_scatter": (n - 1) / n,
        "all_to_all": (n - 1) / n,
        "pt2pt": 1.0,
    }[op]


def run_op_sweep(op: str, sizes_mb: List[float], dtype=jnp.bfloat16,
                 iters: int = 10, algo: str = "exact",
                 emit: bool = False) -> List[Dict]:
    mesh = _mesh_all()
    n = mesh.devices.size
    itemsize = jnp.dtype(dtype).itemsize
    rows = []
    # reduce_scatter consumes a per-rank FULL buffer (in_specs=P()), so place
    # the input replicated; sharding it P('all') would fold an implicit
    # all-gather into the timed region and corrupt the measurement
    in_spec = P() if op == "reduce_scatter" else P("all")
    fn = _collective_fn(op, mesh) if algo == "exact" else None
    for mb in sizes_mb:
        base = max(int(mb * 2 ** 20 / itemsize) // n * n, n)
        numel = -(-base // (n * n)) * n * n      # divisible for every layout
        if algo == "exact":
            x = jax.device_put(jnp.ones((numel,), dtype),
                               NamedSharding(mesh, in_spec))
            timed_fn = fn
        else:
            timed_fn, x = _quantized_setup(op, algo, mesh, numel, dtype)
        dt = _timed(timed_fn, x, iters)
        size_bytes = numel * itemsize
        row = {"op": op, "algo": algo, "axis": "all", "n": n,
               "size_mb": round(size_bytes / 2 ** 20, 3),
               "size_bytes": size_bytes,
               "latency_us": round(dt * 1e6, 1)}
        algbw = size_bytes / dt / 1e9
        row["algbw_gbps"] = round(algbw, 3)
        row["busbw_gbps"] = round(algbw * busbw_factor(op, n), 3)
        rows.append(row)
        if emit:
            print("comm_bench: " + json.dumps(row))
    return rows


def print_table(rows: List[Dict]):
    if not rows:
        return
    cols = list(rows[0])
    widths = [max(len(c), max(len(str(r[c])) for r in rows)) for c in cols]
    line = "  ".join(c.ljust(w) for c, w in zip(cols, widths))
    print(line)
    print("-" * len(line))
    for r in rows:
        print("  ".join(str(r[c]).ljust(w) for c, w in zip(cols, widths)))


# ---------------------------------------------------------------------------
# recorded sweeps + regression compare (dryrun timing-gate convention)
# ---------------------------------------------------------------------------

def record_sweep(rows: List[Dict], path: str) -> str:
    doc = {"n": rows[0]["n"] if rows else len(jax.devices()), "rows": rows}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, path)
    return path


def latest_comm_sweep(baseline_dir: str, n_devices: Optional[int] = None
                      ) -> Tuple[Optional[str], List[Dict]]:
    """(name, rows) of the newest recorded sweep in ``baseline_dir``
    (``COMMBENCH_r*.json`` reports or ``comm_sweep*.json`` recordings);
    sweeps from a different device count are skipped — their latencies
    aren't comparable."""
    from .sweeps import latest_recorded_sweep
    return latest_recorded_sweep(
        baseline_dir, ("COMMBENCH_r*.json", "comm_sweep*.json"), n_devices)


def check_sweep_regression(current: List[Dict], baseline: List[Dict],
                           factor: float = SWEEP_REGRESSION_FACTOR
                           ) -> List[str]:
    """Rows > ``factor`` x their recorded latency, keyed by
    (op, algo, axis, size_mb). Missing rows are NOT flagged (a narrower
    re-run is legitimate; the dryrun gate owns leg-coverage)."""
    def key(r):
        return (r.get("op"), r.get("algo", "exact"), r.get("axis", "all"),
                r.get("size_mb"))

    base = {key(r): float(r["latency_us"]) for r in baseline
            if "latency_us" in r}
    problems = []
    for r in current:
        b = base.get(key(r))
        if b is None or b <= 0 or "latency_us" not in r:
            continue
        now = float(r["latency_us"])
        if now > factor * b:
            problems.append(
                f"{r['op']}/{r.get('algo', 'exact')}@{r.get('size_mb')}MB: "
                f"{now:.1f}us vs recorded {b:.1f}us "
                f"({now / b:.1f}x > {factor:g}x budget)")
    return problems


def main(argv=None):
    p = argparse.ArgumentParser(prog="ds_bench",
                                description="collective benchmark sweeps")
    p.add_argument("--ops", default="all_reduce,all_gather,reduce_scatter,"
                                    "all_to_all,pt2pt")
    p.add_argument("--algos", default="exact",
                   help="comma list of wire formats per op "
                        "(exact,int8,onebit); unsupported (op, algo) "
                        "pairs are skipped")
    p.add_argument("--sizes-mb", default="1,16,64")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--record", default="",
                   help="write the sweep rows to this JSON path (the "
                        "comm-plan selector's input)")
    p.add_argument("--baseline-dir", default=".",
                   help="directory searched for the newest recorded "
                        "sweep to compare against (>2x = loud "
                        "regression; DSTPU_COMM_BENCH_GATE=1 makes it "
                        "fatal)")
    args = p.parse_args(argv)
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
             "float16": jnp.float16}[args.dtype]
    sizes = [float(s) for s in args.sizes_mb.split(",")]
    algos = [a.strip() for a in args.algos.split(",") if a.strip()]
    all_rows = []
    for op in args.ops.split(","):
        op = op.strip()
        for algo in algos:
            if algo not in OP_ALGOS.get(op, ()):
                continue
            all_rows += run_op_sweep(op, sizes, dtype, args.iters,
                                     algo=algo, emit=True)
    print_table(all_rows)
    base_name, baseline = latest_comm_sweep(args.baseline_dir,
                                            len(jax.devices()))
    if baseline:
        problems = check_sweep_regression(all_rows, baseline)
        for prob in problems:
            print(f"comm_bench REGRESSION vs {base_name}: {prob}")
        if not problems:
            print(f"comm_bench within {SWEEP_REGRESSION_FACTOR:g}x of "
                  f"{base_name}")
        elif os.environ.get("DSTPU_COMM_BENCH_GATE") == "1":
            raise SystemExit("comm_bench regression:\n" +
                             "\n".join(problems))
    if args.record:
        print(f"comm_bench recorded: {record_sweep(all_rows, args.record)}")


if __name__ == "__main__":
    main()
