"""Pipeline schedule benchmark — measured bubble, wall-clock, live memory.

Round-3 Weak #3 ("no pipeline performance evidence"): this harness produces
numbers, not claims, for the two schedules:

  * schedule table ticks vs theory: 1F1B's clock-aligned tables
    (runtime/pipe/one_f_one_b.build_1f1b_tables) against the ideal
    n_micro-tick steady state, and GPipe's (pp-1)/(n_micro+pp-1) fill/drain
    bubble (runtime/pipe/schedule.bubble_fraction);
  * wall-clock per optimizer-equivalent step for GPipe-autodiff vs
    1F1B-recompute vs 1F1B-store on the same model and mesh;
  * compiled live-memory (XLA temp allocation) as n_micro grows — the
    "activation memory ∝ stages, not microbatches" claim, measured from
    compile().memory_analysis() instead of asserted structurally.

Run on the virtual CPU mesh (relative numbers; the schedules' compute is
identical so ratios transfer):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m deepspeed_tpu.benchmarks.pipeline_bench

Reference context: the reference claims 2-7x from pipeline parallelism in
low-bandwidth regimes (docs/_pages/training.md:100) — a cross-node claim
this single-host harness does not reproduce; what it pins down is the
schedule overhead itself (bubble + recompute-vs-store).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _bubble_rows(pairs):
    from ..runtime.pipe.one_f_one_b import build_1f1b_tables
    from ..runtime.pipe.schedule import bubble_fraction
    rows = []
    for n_micro, pp in pairs:
        t = build_1f1b_tables(n_micro, pp)
        ticks = t["ticks"]
        # a tick holds one fwd AND one bwd slot; ideal = n_micro ticks
        meas = 1.0 - n_micro / ticks
        rows.append({
            "n_micro": n_micro, "pp": pp, "ticks": int(ticks),
            "ideal_ticks": n_micro,
            "bubble_1f1b_measured": round(meas, 4),
            "bubble_schedule_theory": round(bubble_fraction(n_micro, pp), 4),
        })
    return rows


def _wallclock_and_memory(pp, n_micro, hidden, layers, seq, mb, steps):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ..models import causal_lm_loss
    from ..models.pipeline import build_pipelined_model
    from ..parallel.mesh import MeshManager, set_global_mesh

    mm = MeshManager(pp_size=pp)
    set_global_mesh(mm)
    mesh = mm.mesh
    kw = dict(hidden_size=hidden, num_layers=layers, num_heads=4,
              vocab_size=512, max_seq_len=seq, dtype=jnp.float32,
              attention_impl="reference")

    def variant(backward):
        piped, cfg = build_pipelined_model("gpt2-tiny", pp=pp,
                                           n_micro=n_micro,
                                           backward=backward, **kw)
        params = piped.init(jax.random.PRNGKey(0),
                            {"input_ids": np.zeros((n_micro * mb, seq),
                                                   np.int32)})["params"]
        batch = {"input_ids": np.random.default_rng(0).integers(
            0, 512, size=(n_micro * mb, seq))}
        batch = jax.tree.map(jnp.asarray, batch)
        fn = jax.jit(lambda p, b: piped.train_value_and_grad(
            p, b, mesh=mesh))
        lowered = fn.lower(params, batch)
        compiled = lowered.compile()
        mem = compiled.memory_analysis()
        temp = int(getattr(mem, "temp_size_in_bytes", 0))
        out = compiled(params, batch)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = compiled(params, batch)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / steps
        return dt, temp, params, batch, piped, cfg

    def gpipe(params, batch, piped, cfg):
        # batch traced (not closed over) so the compiled program is
        # structurally comparable to the 1F1B variants
        fn = jax.jit(jax.value_and_grad(
            lambda p, b: causal_lm_loss(
                piped.apply({"params": p}, b, train=False, mesh=mesh), b),
            argnums=0))
        compiled = fn.lower(params, batch).compile()
        mem = compiled.memory_analysis()
        temp = int(getattr(mem, "temp_size_in_bytes", 0))
        out = compiled(params, batch)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = compiled(params, batch)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / steps
        return dt, temp

    t_rec, m_rec, params, batch, piped, cfg = variant("recompute")
    t_sto, m_sto, *_ = variant("store")
    t_gp, m_gp = gpipe(params, batch, piped, cfg)
    return {
        "pp": pp, "n_micro": n_micro, "hidden": hidden, "layers": layers,
        "seq": seq, "mb": mb,
        "step_s": {"gpipe_autodiff": round(t_gp, 4),
                   "1f1b_recompute": round(t_rec, 4),
                   "1f1b_store": round(t_sto, 4)},
        "xla_temp_bytes": {"gpipe_autodiff": m_gp,
                           "1f1b_recompute": m_rec,
                           "1f1b_store": m_sto},
    }


def _ensure_devices(n):
    """Re-exec in a clean subprocess configured for n virtual CPU devices
    when the current process's jax is already pinned to another backend
    (shared recipe: utils/respawn.clean_cpu_env)."""
    import subprocess
    import sys
    import jax
    from ..utils.respawn import clean_cpu_env
    if len(jax.devices()) >= n:
        return False
    env = clean_cpu_env(n)
    env["DSTPU_PIPEBENCH_CHILD"] = "1"
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.benchmarks.pipeline_bench"]
        + sys.argv[1:], env=env)
    sys.exit(proc.returncode)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--pp", type=int, default=4)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--mb", type=int, default=2)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--micros", type=int, nargs="+", default=[4, 8, 16])
    args = p.parse_args(argv)
    import os
    if os.environ.get("DSTPU_PIPEBENCH_CHILD") != "1":
        _ensure_devices(max(args.pp * 2, 8))

    print(json.dumps({"bubble_table": _bubble_rows(
        [(m, args.pp) for m in args.micros]
        + [(8, 2), (16, 8)])}))
    for n_micro in args.micros:
        row = _wallclock_and_memory(args.pp, n_micro, args.hidden,
                                    args.layers, args.seq, args.mb,
                                    args.steps)
        print(json.dumps(row))


if __name__ == "__main__":
    main()
