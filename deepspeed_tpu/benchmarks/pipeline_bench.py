"""Pipeline schedule benchmark — measured bubble, wall-clock, live memory.

Round-3 Weak #3 ("no pipeline performance evidence"): this harness produces
numbers, not claims, for the two schedules:

  * schedule table ticks vs theory: 1F1B's clock-aligned tables
    (runtime/pipe/one_f_one_b.build_1f1b_tables) against the ideal
    n_micro-tick steady state, and GPipe's (pp-1)/(n_micro+pp-1) fill/drain
    bubble (runtime/pipe/schedule.bubble_fraction);
  * wall-clock per optimizer-equivalent step for GPipe-autodiff vs
    1F1B-recompute vs 1F1B-store on the same model and mesh;
  * compiled live-memory (XLA temp allocation) as n_micro grows — the
    "activation memory ∝ stages, not microbatches" claim, measured from
    compile().memory_analysis() instead of asserted structurally.

Run on the virtual CPU mesh (relative numbers; the schedules' compute is
identical so ratios transfer):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python -m deepspeed_tpu.benchmarks.pipeline_bench

Reference context: the reference claims 2-7x from pipeline parallelism in
low-bandwidth regimes (docs/_pages/training.md:100) — a cross-node claim
this single-host harness does not reproduce; what it pins down is the
schedule overhead itself (bubble + recompute-vs-store).
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

#: discovery patterns for recorded pipe sweeps (newest-recorded-sweep
#: convention, shared loader in benchmarks/sweeps.py)
PIPE_BENCH_PATTERNS = ("PIPEBENCH_r*.json", "pipe_bench*.json")


def _bubble_rows(pairs):
    from ..runtime.pipe.one_f_one_b import build_1f1b_tables
    from ..runtime.pipe.schedule import bubble_fraction
    rows = []
    for n_micro, pp in pairs:
        t = build_1f1b_tables(n_micro, pp)
        ticks = t["ticks"]
        # a tick holds one fwd AND one bwd slot; ideal = n_micro ticks
        meas = 1.0 - n_micro / ticks
        rows.append({
            "n_micro": n_micro, "pp": pp, "ticks": int(ticks),
            "ideal_ticks": n_micro,
            "bubble_1f1b_measured": round(meas, 4),
            "bubble_schedule_theory": round(bubble_fraction(n_micro, pp), 4),
        })
    return rows


def _wallclock_and_memory(pp, n_micro, hidden, layers, seq, mb, steps):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from ..models import causal_lm_loss
    from ..models.pipeline import build_pipelined_model
    from ..parallel.mesh import MeshManager, set_global_mesh

    mm = MeshManager(pp_size=pp)
    set_global_mesh(mm)
    mesh = mm.mesh
    kw = dict(hidden_size=hidden, num_layers=layers, num_heads=4,
              vocab_size=512, max_seq_len=seq, dtype=jnp.float32,
              attention_impl="reference")

    def variant(backward):
        piped, cfg = build_pipelined_model("gpt2-tiny", pp=pp,
                                           n_micro=n_micro,
                                           backward=backward, **kw)
        params = piped.init(jax.random.PRNGKey(0),
                            {"input_ids": np.zeros((n_micro * mb, seq),
                                                   np.int32)})["params"]
        batch = {"input_ids": np.random.default_rng(0).integers(
            0, 512, size=(n_micro * mb, seq))}
        batch = jax.tree.map(jnp.asarray, batch)
        fn = jax.jit(lambda p, b: piped.train_value_and_grad(
            p, b, mesh=mesh))
        lowered = fn.lower(params, batch)
        compiled = lowered.compile()
        mem = compiled.memory_analysis()
        temp = int(getattr(mem, "temp_size_in_bytes", 0))
        out = compiled(params, batch)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = compiled(params, batch)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / steps
        return dt, temp, params, batch, piped, cfg

    def gpipe(params, batch, piped, cfg):
        # batch traced (not closed over) so the compiled program is
        # structurally comparable to the 1F1B variants
        fn = jax.jit(jax.value_and_grad(
            lambda p, b: causal_lm_loss(
                piped.apply({"params": p}, b, train=False, mesh=mesh), b),
            argnums=0))
        compiled = fn.lower(params, batch).compile()
        mem = compiled.memory_analysis()
        temp = int(getattr(mem, "temp_size_in_bytes", 0))
        out = compiled(params, batch)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = compiled(params, batch)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / steps
        return dt, temp

    t_rec, m_rec, params, batch, piped, cfg = variant("recompute")
    t_sto, m_sto, *_ = variant("store")
    t_gp, m_gp = gpipe(params, batch, piped, cfg)
    return {
        "pp": pp, "n_micro": n_micro, "hidden": hidden, "layers": layers,
        "seq": seq, "mb": mb,
        "step_s": {"gpipe_autodiff": round(t_gp, 4),
                   "1f1b_recompute": round(t_rec, 4),
                   "1f1b_store": round(t_sto, 4)},
        "xla_temp_bytes": {"gpipe_autodiff": m_gp,
                           "1f1b_recompute": m_rec,
                           "1f1b_store": m_sto},
    }


def _pipe_bench_row(pp, n_micro, hidden, layers, seq, mb, steps):
    """One machine-readable SPMD-vs-MPMD placement row (round 13).

    ``spmd_step_s`` is the 1F1B stacked-scan executor's wall per
    optimizer-equivalent step, ``mpmd_step_s`` the per-stage-programs
    executor on submeshes of the same mesh — same model, same schedule
    tables, so the delta IS the placement cost (host-driven dispatch +
    explicit transfers vs one compiled scan). On jax builds without
    ``jax.shard_map`` the SPMD cell records null (the documented 0.4.x
    gap) and the MPMD cell still anchors the convention.
    """
    import jax
    import jax.numpy as jnp

    from ..models.pipeline import build_pipelined_model
    from ..parallel.mesh import MeshManager, set_global_mesh
    from ..runtime.pipe.schedule import bubble_fraction, build_1f1b_tables

    mm = MeshManager(pp_size=pp)
    set_global_mesh(mm)
    mesh = mm.mesh
    kw = dict(hidden_size=hidden, num_layers=layers, num_heads=4,
              vocab_size=512, max_seq_len=seq, dtype=jnp.float32,
              attention_impl="reference")
    piped, cfg = build_pipelined_model("gpt2-tiny", pp=pp, n_micro=n_micro,
                                       **kw)
    params = piped.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((n_micro * mb, seq),
                                               np.int32)})["params"]
    batch = {"input_ids": jnp.asarray(np.random.default_rng(0).integers(
        0, 512, size=(n_micro * mb, seq)))}

    def timed(fn):
        fn()                                   # compile + warm
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn()
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / steps

    mpmd_s = timed(lambda: piped.mpmd_value_and_grad(params, batch,
                                                     mesh=mesh))
    spmd_s = None
    if hasattr(jax, "shard_map"):
        fn = jax.jit(lambda p, b: piped.train_value_and_grad(p, b,
                                                             mesh=mesh))
        compiled = fn.lower(params, batch).compile()
        spmd_s = timed(lambda: compiled(params, batch))
    t = build_1f1b_tables(n_micro, pp)
    return {
        "pp": pp, "n_micro": n_micro, "hidden": hidden, "layers": layers,
        "seq": seq, "mb": mb,
        "spmd_step_s": None if spmd_s is None else round(spmd_s, 4),
        "mpmd_step_s": round(mpmd_s, 4),
        "bubble_theory": round(bubble_fraction(n_micro, pp), 4),
        "bubble_1f1b_measured": round(1.0 - n_micro / t["ticks"], 4),
    }


def _row_key(row):
    return (row.get("pp"), row.get("n_micro"), row.get("hidden"),
            row.get("layers"), row.get("seq"), row.get("mb"))


def latest_pipe_bench(baseline_dir: str, n_devices=None):
    """(basename, rows) of the newest recorded pipe sweep matching this
    device count — the shared newest-recorded-sweep convention."""
    from .sweeps import latest_recorded_sweep
    return latest_recorded_sweep(baseline_dir, PIPE_BENCH_PATTERNS,
                                 n_devices=n_devices)


def check_pipe_regression(rows, baseline_rows):
    """Messages for rows whose mpmd wall/step regressed > 2x vs the
    recorded sweep (CI-host speed varies ~30%; 2x is signal). SPMD cells
    compare only when both sweeps have one."""
    base = {_row_key(r): r for r in baseline_rows}
    msgs = []
    for row in rows:
        ref = base.get(_row_key(row))
        if ref is None:
            continue
        for field in ("mpmd_step_s", "spmd_step_s"):
            new, old = row.get(field), ref.get(field)
            if new and old and new > 2.0 * old:
                msgs.append(
                    f"pipe_bench regression {field} "
                    f"pp={row['pp']} n_micro={row['n_micro']}: "
                    f"{new:.4f}s vs recorded {old:.4f}s (>2x)")
    return msgs


def _record_sweep(rows, baseline_dir):
    import jax
    doc = {"n": len(jax.devices()), "rows": rows}
    os.makedirs(baseline_dir, exist_ok=True)
    k = 1
    while os.path.exists(os.path.join(baseline_dir,
                                      f"PIPEBENCH_r{k:02d}.json")):
        k += 1
    path = os.path.join(baseline_dir, f"PIPEBENCH_r{k:02d}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    return path


def _ensure_devices(n):
    """Re-exec in a clean subprocess configured for n virtual CPU devices
    when the current process's jax is already pinned to another backend
    (shared recipe: utils/respawn.clean_cpu_env)."""
    import subprocess
    import sys
    import jax
    from ..utils.respawn import clean_cpu_env
    if len(jax.devices()) >= n:
        return False
    env = clean_cpu_env(n)
    env["DSTPU_PIPEBENCH_CHILD"] = "1"
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.benchmarks.pipeline_bench"]
        + sys.argv[1:], env=env)
    if proc.returncode == -6:
        # older jaxlibs hard-abort on the raised CPU-collective timeout
        # flags ("Unknown flags in XLA_FLAGS") — retry without them, the
        # dryrun_multichip recipe
        env = clean_cpu_env(n, collective_timeout_flags=False)
        env["DSTPU_PIPEBENCH_CHILD"] = "1"
        proc = subprocess.run(
            [sys.executable, "-m",
             "deepspeed_tpu.benchmarks.pipeline_bench"]
            + sys.argv[1:], env=env)
    sys.exit(proc.returncode)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--pp", type=int, default=4)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--mb", type=int, default=2)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--micros", type=int, nargs="+", default=[4, 8, 16])
    p.add_argument("--placements", action="store_true",
                   help="also run the SPMD-vs-MPMD placement rows "
                        "(pipe_bench: lines, round 13)")
    p.add_argument("--record", action="store_true",
                   help="write the placement rows as the next "
                        "PIPEBENCH_r<k>.json under --baseline-dir")
    p.add_argument("--baseline-dir", default=".", dest="baseline_dir")
    args = p.parse_args(argv)
    if os.environ.get("DSTPU_PIPEBENCH_CHILD") != "1":
        _ensure_devices(max(args.pp * 2, 8))

    print(json.dumps({"bubble_table": _bubble_rows(
        [(m, args.pp) for m in args.micros]
        + [(8, 2), (16, 8)])}))
    import jax
    if hasattr(jax, "shard_map"):
        for n_micro in args.micros:
            row = _wallclock_and_memory(args.pp, n_micro, args.hidden,
                                        args.layers, args.seq, args.mb,
                                        args.steps)
            print(json.dumps(row))
    else:
        print(json.dumps({"skipped": "spmd wallclock/memory rows: this "
                          "jax build has no jax.shard_map (0.4.x)"}))
    if not (args.placements or args.record):
        return
    rows = []
    for n_micro in args.micros:
        row = _pipe_bench_row(args.pp, n_micro, args.hidden, args.layers,
                              args.seq, args.mb, args.steps)
        print("pipe_bench: " + json.dumps(row))
        rows.append(row)
    _name, base_rows = latest_pipe_bench(args.baseline_dir,
                                         n_devices=len(jax.devices()))
    msgs = check_pipe_regression(rows, base_rows)
    for m in msgs:
        print("pipe_bench REGRESSION: " + m)
    if msgs and os.environ.get("DSTPU_PIPE_BENCH_GATE") == "1":
        raise SystemExit("pipe_bench regression gate tripped")
    if args.record:
        print("recorded " + _record_sweep(rows, args.baseline_dir))


if __name__ == "__main__":
    main()
