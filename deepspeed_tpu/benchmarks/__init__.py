"""Benchmark harnesses (collective sweeps = the reference's ds_bench)."""
