"""Training-throughput benchmark: model-flops TFLOPs/chip for any preset.

Covers the reference's headline training benchmarks (BASELINE.md):
  - BERT-large seq128: 64 TFLOPS/GPU (docs/_posts/2020-05-28-fastest-bert
    -training.md:36) and seq512: 53 TFLOPS/GPU
  - GPT-2 sustained training throughput: 50 TFLOPS/GPU
    (docs/_posts/2021-03-08-zero3-offload.md:65)

The repo-root ``bench.py`` (the driver's entry) is the GPT-2 instance of this
loop; this module generalizes it so ``ds_bench --training bert-large`` can
reproduce every headline row on TPU.
"""

import json
import time

import numpy as np

def _bf16_peak_tflops():
    """Per-chip bf16 peak by device kind (None when unknown — a wrong MFU
    is worse than no MFU)."""
    import jax
    if jax.default_backend() != "tpu":
        return None
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for key, peak in (("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0),
                      ("v6", 918.0), ("v4", 275.0)):
        if key in kind:
            return peak
    return None


# reference headline numbers to report "vs" (V100, see BASELINE.md)
REFERENCE_TFLOPS = {
    ("bert-large", 128): 64.0,
    ("bert-large", 512): 53.0,
    ("gpt2-350m", 1024): 50.0,
    ("gpt2-1.3b", 1024): 50.0,
}


def run_training_bench(preset: str = "bert-large", seq: int = 128,
                       micro: int = 64, gas: int = 1, steps: int = 4,
                       zero_stage: int = 1, remat: bool = False,
                       remat_policy: str = "dots", fused_loss=None,
                       pure_bf16: bool = False,
                       grad_accum_dtype=None,
                       masked=None,
                       low_precision=None,
                       verbose: bool = True,
                       **model_kw):
    """Measure sustained train-step model TFLOPs/chip for a preset.

    Extra keyword args flow into ``build_model`` (``attention_impl``,
    ``moe_experts``, ``moe_k``, …) so long-context and MoE variants run
    through the same timing loop. Returns the result dict (also printed as
    one JSON line when verbose).

    ``masked`` (default: True for BERT presets): batches carry a ragged
    attention_mask — sample lengths uniform in [seq/4, seq], the layout real
    padded-batch training sees. The mask rides the Pallas flash kernel
    in-kernel, so this leg times the representative path instead of the
    maskless upper bound (a maskless encoder leg never exercises the mask
    plumbing the reference's fused softmax kernels exist for).
    """
    import jax
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import (build_model, fused_loss_passthrough,
                                      make_moe_loss)
    from deepspeed_tpu.models.transformer import causal_lm_loss, cross_entropy

    n_chips = len(jax.devices())
    causal = not preset.startswith("bert")
    if fused_loss is None:
        # measured on v5e: the chunked fused CE wins for causal seq>=1024
        # (avoids [B,S,50257] fp32 logits) but LOSES ~20% for BERT seq128
        # (logits fit; the checkpoint-recompute costs more than it saves)
        fused_loss = causal
    kw = dict(max_seq_len=max(seq, 512), remat=remat,
              remat_policy=remat_policy, fused_loss=fused_loss,
              loss_chunk=256)
    if low_precision:
        # round-17 experiment: int8/fp8 fake-quant on every block matmul
        # input (quant_format.fake_quant_act, STE) — sentinel-gated below
        kw["activation_quant"] = low_precision
    kw.update(model_kw)
    model, cfg = build_model(preset, **kw)
    batch_size = micro * gas * max(n_chips, 1)
    config = {
        "train_batch_size": batch_size,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        # pure_bf16: params-are-master + bf16 moments (BF16Config.
        # master_weights) — the device-resident route to 1.3B on one 16GB
        # chip (host offload is relay-bandwidth-starved in this environment)
        "bf16": ({"enabled": True, "master_weights": False} if pure_bf16
                 else {"enabled": True}),
        "zero_optimization": {"stage": zero_stage},
        "steps_per_print": 10_000,
    }
    if grad_accum_dtype:
        config["data_types"] = {"grad_accum_dtype": grad_accum_dtype}
    if low_precision:
        # the experiment ships with its guardrail: the integrity sentinel's
        # skip/rollback ladder (the engine refuses the flag without it)
        config["integrity"] = {"enabled": True}
    rng = np.random.default_rng(0)
    if masked is None:
        masked = not causal

    def make_batch():
        b = {"input_ids": rng.integers(0, cfg.vocab_size,
                                       size=(batch_size, seq))}
        if masked:
            lens = rng.integers(max(seq // 4, 1), seq + 1, size=(batch_size,))
            b["attention_mask"] = (np.arange(seq)[None, :]
                                   < lens[:, None]).astype(np.int32)
        return b

    # fused_loss models return the scalar loss (BERT variant predicts in
    # place — same cost profile as the reference's MLM objective); plain
    # models emit [B,S,V] logits scored with token-level CE
    loss_fn = (fused_loss_passthrough if fused_loss
               else (causal_lm_loss if causal else
                     lambda out, b: cross_entropy(
                         out, b.get("labels", b["input_ids"]))))
    if cfg.moe_experts > 0:
        # MoE models emit (task_output, aux); fold the aux term in the same
        # way training does so the timed step is the real thing
        loss_fn = make_moe_loss(cfg.moe_aux_weight, base_loss=loss_fn)
    engine, *_ = ds.initialize(model=model, config=config, loss_fn=loss_fn,
                               example_batch=make_batch())
    float(engine.train_batch(make_batch())["loss"])   # compile
    float(engine.train_batch(make_batch())["loss"])   # steady state

    # per-step timings, each fenced on its own loss (the axon relay's
    # block_until_ready does not fence; float() forces a real D2H) —
    # median + spread instead of a single mean over an unfenced window
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        m = engine.train_batch(make_batch())
        float(m["loss"])
        times.append(time.perf_counter() - t0)
    dt = float(np.median(times))
    spread = (max(times) - min(times)) / dt if dt else 0.0

    # FLOPs accounting: the 6N basis is what the reference's TFLOPS/GPU
    # numbers use (attention-free); the attention matmul term (12*L*H*S per
    # token fwd+bwd) is reported separately so MFU is honest
    n_params = cfg.num_active_params()
    tokens = batch_size * seq
    model_flops = 6.0 * n_params * tokens
    attn_flops = 12.0 * cfg.num_layers * cfg.hidden_size * seq * tokens
    tflops = model_flops / dt / max(n_chips, 1) / 1e12
    tflops_attn = (model_flops + attn_flops) / dt / max(n_chips, 1) / 1e12
    peak = _bf16_peak_tflops()
    ref = REFERENCE_TFLOPS.get((preset, seq))
    out = {
        "metric": f"{preset}_seq{seq}_train_tflops_per_chip",
        "value": round(tflops, 3),
        "unit": "TFLOPs/chip",
        "vs_baseline": round(tflops / ref, 4) if ref else None,
        "detail": {"preset": preset, "seq": seq, "micro": micro, "gas": gas,
                   "batch": batch_size, "chips": n_chips,
                   **({"moe_experts": cfg.moe_experts, "moe_k": cfg.moe_k,
                       "params_total": cfg.num_params(),
                       "params_active": n_params}
                      if cfg.moe_experts > 0 else {}),
                   **({"attention_impl": cfg.attention_impl}
                      if cfg.attention_impl != "auto" else {}),
                   "masked": bool(masked),
                   "zero_stage": zero_stage, "remat": remat,
                   "remat_policy": remat_policy if remat else None,
                   "pure_bf16": pure_bf16,
                   **({"low_precision": low_precision} if low_precision
                      else {}),
                   "grad_accum_dtype": grad_accum_dtype or "fp32",
                   "step_time_s": round(dt, 4),
                   "step_time_spread": round(spread, 4),
                   "steps_timed": steps,
                   "step_times_s": [round(t, 4) for t in times],
                   "tflops_incl_attention": round(tflops_attn, 3),
                   "mfu_incl_attention": (round(tflops_attn / peak, 4)
                                          if peak else None),
                   "samples_per_s": round(batch_size / dt, 2),
                   "backend": jax.default_backend()},
    }
    if verbose:
        print(json.dumps(out))
    return out


def main(argv=None):
    import argparse
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="bert-large")
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--micro", type=int, default=64)
    p.add_argument("--gas", type=int, default=1)
    p.add_argument("--steps", type=int, default=4)
    p.add_argument("--zero", type=int, default=1)
    p.add_argument("--remat", action="store_true")
    p.add_argument("--remat-policy", default="dots",
                   help="full | dots | offload (see TransformerConfig)")
    fl = p.add_mutually_exclusive_group()
    fl.add_argument("--fused-loss", dest="fused_loss", default=None,
                    action="store_true",
                    help="force the chunked fused CE (default: causal only)")
    fl.add_argument("--no-fused-loss", dest="fused_loss",
                    action="store_false",
                    help="force the plain [B,S,V]-logits loss")
    mk = p.add_mutually_exclusive_group()
    mk.add_argument("--masked", dest="masked", default=None,
                    action="store_true",
                    help="ragged attention_mask batches (default for BERT)")
    mk.add_argument("--no-masked", dest="masked", action="store_false",
                    help="maskless batches (the pre-round-6 upper bound)")
    p.add_argument("--low-precision", choices=("int8", "fp8"), default=None,
                   help="round-17 experiment: fake-quant block matmul "
                        "inputs (sentinel-gated; e.g. --preset gpt2-350m)")
    a = p.parse_args(argv)
    run_training_bench(a.preset, a.seq, a.micro, a.gas, a.steps, a.zero,
                       a.remat, remat_policy=a.remat_policy,
                       fused_loss=a.fused_loss, masked=a.masked,
                       low_precision=a.low_precision)


if __name__ == "__main__":
    main()
