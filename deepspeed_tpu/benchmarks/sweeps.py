"""Shared newest-recorded-sweep discovery for the bench regression
gates (COMMBENCH / SERVEBENCH / dryrun-timings convention): find the
most recent JSON report in a directory whose ``{"n": device_count,
"rows": [...]}`` document matches the current topology — sweeps from a
different device count are skipped, their numbers aren't comparable."""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple


def latest_recorded_sweep(baseline_dir: str, patterns: Sequence[str],
                          n_devices: Optional[int] = None
                          ) -> Tuple[Optional[str], List[Dict]]:
    """(basename, rows) of the newest parseable report under
    ``baseline_dir`` matching any of ``patterns`` (newest mtime first);
    unreadable/row-less docs and other-device-count sweeps are
    skipped."""
    paths = sorted(
        (p for pat in patterns
         for p in glob.glob(os.path.join(baseline_dir, pat))),
        key=os.path.getmtime, reverse=True)
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        rows = doc.get("rows") if isinstance(doc, dict) else None
        if not rows:
            continue
        if n_devices is not None and doc.get("n") is not None and \
                int(doc["n"]) != int(n_devices):
            continue
        return os.path.basename(path), rows
    return None, []
