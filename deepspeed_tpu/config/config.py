"""DeepSpeedConfig — typed parse of a ds_config.json / dict.

Capability parity with the reference's ``deepspeed/runtime/config.py`` (DeepSpeedConfig,
~25 typed sections, batch-size triangulation) and ``config_utils.py`` (pydantic
DeepSpeedConfigModel with deprecated-field migration). Rebuilt on pydantic v2 with
TPU-native additions: a first-class ``tensor_parallel`` / ``sequence_parallel`` section
(the reference delegates training TP to an external mpu object) and mesh-axis sizes.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, model_validator

from . import constants as C


class DeepSpeedConfigModel(BaseModel):
    """Base for all config sections: ignore-and-warn unknown keys, populate by alias."""
    model_config = ConfigDict(extra="allow", populate_by_name=True,
                              validate_assignment=True, protected_namespaces=())

    def get(self, key, default=None):
        return getattr(self, key, default)


# ---------------------------------------------------------------------------
# Precision
# ---------------------------------------------------------------------------

class FP16Config(DeepSpeedConfigModel):
    """reference: runtime/constants.py:132-176"""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 = dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False
    # TPU extension: master_weights=false is PURE-bf16 training — params ARE
    # the master and Adam moments store bf16 (math still f32 in-register).
    # 6 bytes/param of state instead of 18: the device-resident path to
    # beyond-HBM scale when host offload is bandwidth-starved.
    master_weights: bool = True


class AMPConfig(DeepSpeedConfigModel):
    enabled: bool = False
    opt_level: str = "O1"


# ---------------------------------------------------------------------------
# ZeRO
# ---------------------------------------------------------------------------

class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """reference: runtime/zero/offload_config.py"""
    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    """reference: runtime/zero/config.py:78-260"""
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = 1_000_000_000
    cpu_offload: Optional[bool] = None          # deprecated alias -> offload_optimizer
    cpu_offload_params: Optional[bool] = None   # deprecated alias -> offload_param
    prefetch_bucket_size: int = Field(50_000_000, alias="stage3_prefetch_bucket_size")
    param_persistence_threshold: int = Field(100_000, alias="stage3_param_persistence_threshold")
    model_persistence_threshold: int = Field(int(1e30), alias="stage3_model_persistence_threshold")
    max_live_parameters: int = Field(1_000_000_000, alias="stage3_max_live_parameters")
    max_reuse_distance: int = Field(1_000_000_000, alias="stage3_max_reuse_distance")
    gather_16bit_weights_on_model_save: bool = Field(
        False, alias="stage3_gather_16bit_weights_on_model_save")
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    # ZeRO++ qwZ: stage-3 param gathers carry int8 shards + scales (1/4 the
    # bf16 gather bytes). Trades the per-layer streaming gathers for one
    # whole-tree quantized gather per microbatch — right when gather
    # bandwidth (DCN) is the bottleneck, wrong when HBM capacity is (the
    # gathered bf16 weights are all resident at once)
    zero_quantized_weights: bool = False

    @model_validator(mode="after")
    def _migrate_deprecated(self):
        if self.cpu_offload and self.offload_optimizer is None:
            self.offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig(device="cpu")
        if self.cpu_offload_params and self.offload_param is None:
            self.offload_param = DeepSpeedZeroOffloadParamConfig(device="cpu")
        if self.overlap_comm is None:
            self.overlap_comm = self.stage == 3
        return self


# ---------------------------------------------------------------------------
# Activation checkpointing / sparse attention
# ---------------------------------------------------------------------------

class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """reference: runtime/activation_checkpointing/config.py"""
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class SparseAttentionConfig(DeepSpeedConfigModel):
    """reference: runtime/config.py:270-453; modes map onto our block-sparse
    mask builders. CONSUMED by engine.wire_attention_config: the section is
    wired into the in-tree model's attention_impl="sparse" (unknown modes
    raise at initialize)."""
    mode: str = "fixed"
    block: int = 16
    different_layout_per_head: bool = False
    # fixed
    num_local_blocks: int = 4
    num_global_blocks: int = 1
    attention: str = "bidirectional"
    horizontal_global_attention: bool = False
    num_different_global_patterns: int = 1
    # variable
    num_random_blocks: int = 0
    local_window_blocks: List[int] = Field(default_factory=lambda: [4])
    global_block_indices: List[int] = Field(default_factory=lambda: [0])
    global_block_end_indices: Optional[List[int]] = None
    # bigbird / bslongformer
    num_sliding_window_blocks: int = 3


# ---------------------------------------------------------------------------
# Aux sections
# ---------------------------------------------------------------------------

class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    group: Optional[str] = None
    team: Optional[str] = None
    project: str = "deepspeed"


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(default_factory=list)


class AIOConfig(DeepSpeedConfigModel):
    """reference: runtime/swap_tensor/constants.py:17-26"""
    block_size: int = 1048576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class ElasticityConfig(DeepSpeedConfigModel):
    """reference: elasticity/constants.py"""
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.1
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True


class AutotuningConfig(DeepSpeedConfigModel):
    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = False
    metric: str = "throughput"
    start_profile_step: int = 3
    end_profile_step: int = 5
    num_tuning_micro_batch_sizes: int = 3
    tuner_type: str = "gridsearch"
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    arg_mappings: Optional[Dict[str, str]] = None
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = 1
    max_train_micro_batch_size_per_gpu: Optional[int] = None
    min_train_micro_batch_size_per_gpu: int = 1
    num_nodes: Optional[int] = None
    num_gpus: Optional[int] = None


class PipelineConfig(DeepSpeedConfigModel):
    """reference: runtime/config.py:454-467 + pipe/module.py kwargs"""
    stages: int = 1
    partition: str = "parameters"   # uniform | parameters | type:regex
    seed_layers: bool = False
    activation_checkpoint_interval: int = 0
    micro_batches: Optional[int] = None
    # "gpipe": AD through the scan (memory ∝ n_micro, f32 boundary);
    # "1f1b": hand-scheduled interleave (memory ∝ stages, bf16 boundary) —
    # the reference TrainSchedule's execution regime
    schedule: str = "gpipe"
    # schedule/placement split (round 13, docs/PIPELINE.md): "spmd" runs
    # the stacked-stage single-program executors; "mpmd" runs each stage
    # as its own jit program on its own submesh, connected by the explicit
    # transfer channel (runtime/pipe/mpmd) — per-stage compiles, per-stage
    # failure domains. Both placements execute the same clock tables.
    placement: str = "spmd"


class TensorParallelConfig(DeepSpeedConfigModel):
    """TPU-native addition: first-class training TP (reference delegates to external mpu)."""
    tp_size: int = 1
    autotp: bool = True


class SequenceParallelConfig(DeepSpeedConfigModel):
    """TPU-native addition: ring-attention / Ulysses-style context parallelism
    over ICI. ``mode`` is CONSUMED by engine.wire_attention_config: with
    sp_size > 1 it selects the in-tree model's ring vs ulysses
    attention_impl (hand-set conflicting impls raise)."""
    sp_size: int = 1
    mode: str = "ring"   # ring | ulysses


class MoEConfig(DeepSpeedConfigModel):
    """Engine-level MoE knobs (the reference configures MoE per-layer in code)."""
    enabled: bool = False
    ep_size: int = 1
    num_experts: int = 1
    top_k: int = 1
    capacity_factor: float = 1.0
    eval_capacity_factor: float = 1.0
    min_capacity: int = 4
    noisy_gate_policy: Optional[str] = None
    drop_tokens: bool = True
    use_rts: bool = True


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = Field(default_factory=dict)
    async_save: bool = False   # TPU-native: orbax-style async checkpointing
    # -- crash-safety knobs (TPU-native; see docs/RESILIENCE.md) ------------
    keep_last: Optional[int] = None   # retention: keep newest K tags (None = all)
    keep_every: int = 0               # + every tag whose step % keep_every == 0
    write_retries: int = 3            # async writer: transient-IO retries
    write_retry_backoff: float = 0.05  # exponential-backoff base, seconds
    verify_load: bool = True          # digest-verify tags at load/rollback


class DataTypesConfig(DeepSpeedConfigModel):
    grad_accum_dtype: Optional[str] = None


class NonFiniteGuardConfig(DeepSpeedConfigModel):
    """DEPRECATED alias (round 7): the streak/abort semantics are folded
    into the training-integrity sentinel as one code path —
    ``abort_after`` here maps onto ``integrity.nonfinite_abort_after``
    (which wins when both are set). Behavior is unchanged: the train step
    skips-and-counts non-finite updates in-jit
    (TrainState.nonfinite_streak), the host check rides the batched
    ``_after_step`` metrics pull (detection latency ``steps_per_print``
    steps unless the sentinel's every-step pull is on), and the abort is
    raised after N CONSECUTIVE skipped steps (0 = never)."""
    abort_after: int = 0


class IntegrityConfig(DeepSpeedConfigModel):
    """TPU-native (round 7): the training-integrity sentinel
    (runtime/sentinel.py, docs/RESILIENCE.md). ``enabled`` turns on the
    host-side anomaly detector over per-step statistics the compiled step
    already computes (loss, global grad norm, update norm, param norm —
    all riding the ONE batched device_get in ``_after_step``) and the
    remediation ladder: in-jit skip of spiked batches (``skip``), then
    auto-rollback to the newest intact checkpoint after
    ``rollback_after`` strikes inside ``strike_window`` steps, then abort
    with rc 118 when the anomaly reproduces after
    ``abort_after_rollbacks`` rollbacks. ``audit_interval`` > 0 adds the
    cross-replica SDC audit: a bit-exact in-jit checksum of every
    fully-replicated state leaf, compared across replicas every N steps;
    a minority replica stamps an ``SDC`` heartbeat flag and the run
    aborts with rc 118 so the relaunch resumes from the last
    audited-clean checkpoint. ``nonfinite_abort_after`` is the folded-in
    PR-3 non-finite guard (``nonfinite_guard.abort_after`` remains as a
    deprecated alias)."""
    enabled: bool = False
    # -- detector ------------------------------------------------------------
    metrics: List[str] = Field(
        default_factory=lambda: ["loss", "grad_norm", "update_norm"])
    window: int = 64           # rolling median/MAD sample window
    zmax: float = 8.0          # robust z-score anomaly threshold
    warmup_steps: int = 20     # accepted samples before any verdict
    cooldown_steps: int = 5    # steps one anomaly event covers (one strike)
    # -- remediation ladder --------------------------------------------------
    skip: bool = True               # rung 1: in-jit skip past the ceiling
    rollback_after: int = 3         # strikes in window -> rung 2
    strike_window: int = 50         # steps
    abort_after_rollbacks: int = 1  # reproduced post-rollback -> rung 3
    load_dir: Optional[str] = None  # rollback source (default: last dir the
    #                                 engine saved to / loaded from)
    # -- SDC audit -----------------------------------------------------------
    audit_interval: int = 0    # steps between cross-replica audits; 0 = off
    # -- folded-in non-finite guard -----------------------------------------
    nonfinite_abort_after: int = 0


class StragglerConfig(DeepSpeedConfigModel):
    """TPU-native (round 15): straggler defense (runtime/straggler.py,
    docs/RESILIENCE.md). The *slow* leg of the threat model — a
    slow-but-alive host (thermal throttling, degraded NIC, noisy
    neighbor) passes every dead/wrong check while the synchronous step
    drags the whole world to its pace. Every worker stamps a rolling
    per-step wall-time gauge (``step_ms``) into its heartbeat records
    regardless of this section (``dstpu health`` RATE column); with
    ``enabled`` a cross-rank detector (the sentinel's median/MAD
    machinery applied across ranks, leave-one-out: the judged rank
    never sits in its own baseline) issues warmup-gated,
    cooldown-debounced verdicts when a rank's step time sits ``zmax``
    robust sigmas above the OTHER ranks' median AND above
    ``rel_threshold`` x that median for ``strike_window`` consecutive
    windows — the relative floor means a UNIFORMLY slow world (everyone
    throttled alike) produces zero verdicts. A verdicted rank stamps a sticky
    ``STRAGGLER`` heartbeat flag (blacklist evidence, the SDC-flag
    pattern); with ``abort_after > 0`` a rank still slow that many
    windows past its verdict exits rc 117 so the elastic agent
    relaunches the world without the slow host. ``abort_after = 0``
    (default) is evidence-only: nothing is ever torn down. The same
    section under ``serving.fleet.straggler`` drives the fleet-side
    slow-replica DRAIN (serving/fleet.py)."""
    enabled: bool = False
    window: int = 8            # worker-side rolling step_ms gauge window
    check_interval: float = 5.0  # engine-side seconds between detection windows
    zmax: float = 6.0          # robust sigmas above the world median
    rel_threshold: float = 1.5  # AND this multiple of the world median
    warmup: int = 3            # complete windows before any verdict
    strike_window: int = 3     # consecutive slow windows -> verdict
    cooldown: int = 10         # windows one verdict debounces
    abort_after: int = 0       # post-verdict windows -> rc 117; 0 = never


class WatchdogConfig(DeepSpeedConfigModel):
    """TPU-native (rounds 4+6): in-worker PHASE-AWARE watchdog. A wedged
    rank in a multi-controller job silently deadlocks every collective in
    the pod; the engine reports lifecycle phases (RESTORE → COMPILE →
    STEP → SAVE, runtime/heartbeat.py) and each phase gets its own
    deadline — a gap beyond it dumps all thread stacks and exits the
    distinct stall rc (runtime/watchdog.py: STALL_EXIT_CODE) so the
    launcher-side supervisor tears the world down and the elastic agent
    restarts — counted against its budget, unlike a preemption. 0 leaves
    a phase unbounded. ``stall_timeout`` bounds steady-state STEP gaps;
    ``compile_timeout`` the first-train_batch-entry → first-completed-step
    window (the compile hang the round-4 watchdog could not see);
    ``restore_timeout`` a checkpoint load; ``save_timeout`` a checkpoint
    write (0 keeps the round-4 suspend-through-saves behavior). The
    watchdog still suspends through the preemption grace window. The
    related bound on ``jax.distributed.initialize`` (the INIT phase) is
    NOT a ds_config knob — it must act before any config is parsed: set
    ``DSTPU_INIT_TIMEOUT`` (forwarded to remote hosts by dstpu),
    ``launch.py --init_timeout``, or the ``initialization_timeout=``
    kwarg of ``init_distributed``. See docs/RESILIENCE.md."""
    stall_timeout: float = 0.0    # STEP: secs without a step heartbeat; 0 = off
    poll_interval: float = 0.0    # check cadence; 0 = min active deadline / 4
    compile_timeout: float = 0.0  # COMPILE: first entry -> first step; 0 = off
    restore_timeout: float = 0.0  # RESTORE: load_checkpoint bound; 0 = off
    save_timeout: float = 0.0     # SAVE: save bound; 0 = unbounded (suspend)
    serve_timeout: float = 0.0    # SERVE: serving-loop iteration gap; 0 = off


class AutoscaleConfig(DeepSpeedConfigModel):
    """TPU-native (round 19): traffic-shaped replica autoscaling
    (``serving/autoscale.py``, docs/SERVING.md §Autoscaling). With
    ``enabled`` the FleetSupervisor/ProcessFleet feed their own SERVE
    heartbeat gauges (queue depth, active lanes, deadline pressure)
    through an AutoscalePolicy each poll: ``up_after`` consecutive
    overloaded observations (queue deeper than ``up_queue_per_replica``
    per live replica, or any queued request within ``pressure_s`` of its
    deadline) spawn ONE warmed replica; a trough of ``down_idle_s``
    seconds with an empty queue drains the newest replica through the
    straggler-drain path (admission stops, lanes finish, then teardown
    — never a mid-lane kill). ``cooldown_s`` debounces both directions
    so a single burst cannot flap, and no verdict at all is issued
    while a replica is still warming (its silence is compile, not
    idleness). Bounds: ``min_replicas`` <= live <= ``max_replicas``.
    Every scale event lands in the heartbeat channel (`dstpu health`
    rank 999) and in ``fleet.scale_events``."""
    enabled: bool = False
    min_replicas: int = 1              # scale-down floor
    max_replicas: int = 4              # scale-up ceiling
    up_queue_per_replica: int = 4      # queue depth per live replica = overload
    pressure_s: float = 0.0            # queued-TTL window that reads as
    #                                    deadline pressure; 0 = off
    up_after: int = 2                  # consecutive overloaded polls -> up
    down_idle_s: float = 10.0          # idle-trough duration -> down
    cooldown_s: float = 15.0           # min seconds between scale events


class FleetConfig(DeepSpeedConfigModel):
    """TPU-native (round 11): the supervised multi-replica serving fleet
    (``serving/fleet.py``, docs/SERVING.md §Fleet). With ``replicas > 1``
    the serving tier runs N continuous-batching replica engines (weights
    shared, KV pools per-replica) behind ONE bounded admission queue. A
    FleetSupervisor consumes each replica's SERVE heartbeat records
    (runtime/heartbeat.py): a dead worker or ``heartbeat_timeout``
    seconds of record silence — the rc-117 contract applied fleet-side —
    tears down only that replica, requeues its in-flight requests with
    exactly-once token emission, and restarts it; ``blacklist_after``
    strikes quarantine a repeatedly-dying replica, and when live replicas
    would drop below ``min_replicas`` the least-struck blacklisted one is
    paroled back (the elastic agent's machinery, applied to serving).
    ``retry_budget`` bounds requeues per request — past it the request
    concludes FAILED instead of looping. ``default_deadline_s`` is the
    queue-wait TTL applied to requests submitted without one (0 = none);
    expired queued requests are shed with TIMEOUT (graceful admission
    backpressure). ``heartbeat_dir`` points the per-replica channel at a
    directory ``dstpu health`` can read (default: a private tempdir,
    exposed as ``ServingFleet.heartbeat_dir``)."""
    replicas: int = 1                  # 1 = plain single-engine serving
    # replica placement (round 18, serving/procfleet.py): "thread" runs
    # replica engines as threads in this process (the round-11 fleet);
    # "process" runs each replica as a supervised OS PROCESS — weights
    # via checkpoint load, request/token streams over the transfer
    # fabric's TCP star (runtime/fabric/), SERVE heartbeats with gauges
    # in the shared channel, warmed restart on death — the
    # fleet-across-a-pod shape. Process placement requires plain
    # replicas (disagg roles share one in-process pool by construction).
    placement: str = "thread"          # "thread" | "process"
    # disaggregated serving (round 12, serving/disagg.py): with BOTH > 0
    # the fleet runs prefill-role and decode-role replicas over ONE
    # shared paged-KV state, connected by the bounded block-handoff
    # queue — `replicas` is ignored in favor of the role counts. A dead
    # prefill replica's half-prefilled request requeues exactly-once
    # (partial blocks released, chunk progress carried in the death
    # ledger); a dead decode replica requeues through the token-exact
    # prompt+emitted path.
    prefill_replicas: int = 0          # disagg prefill-role replicas
    decode_replicas: int = 0           # disagg decode-role replicas
    retry_budget: int = 2              # requeues per request before FAILED
    heartbeat_timeout: float = 10.0    # replica record silence -> dead
    heartbeat_interval: float = 0.25   # replica writer min_interval
    poll_interval: float = 0.5         # supervisor check cadence
    blacklist_after: int = 3           # strikes before quarantine; 0 = never
    min_replicas: int = 1              # parole floor for live replicas
    max_queue: int = 4096              # shared admission queue bound
    default_deadline_s: float = 0.0    # queue-wait TTL; 0 = none
    heartbeat_dir: Optional[str] = None  # None = private tempdir
    # priority lanes (round 19, serving/scheduler.py TieredQueue):
    # submit(priority=) picks latency/standard/batch; dispatch serves
    # the highest tier first, FIFO within a tier, and a head that has
    # waited longer than ``priority_aging_s`` is served regardless of
    # tier (the starvation floor). A latency-tier request within
    # ``preempt_pressure_s`` of its deadline (or waiting past it with
    # no deadline set) may PREEMPT a running batch-tier lane: the
    # victim requeues through the exactly-once token-exact path
    # (emitted prefix carried, no retry_budget charge). 0 disables
    # preemption. ``batch_highwater`` is the admission ladder's soft
    # rung: once the queue is past this fraction of max_queue, new
    # batch-tier submissions get a machine-readable AdmissionRejected
    # instead of deepening the backlog — saturation degrades batch
    # before latency.
    priority_aging_s: float = 30.0     # tier starvation floor (seconds)
    preempt_pressure_s: float = 0.0    # latency deadline slack -> preempt;
    #                                    0 = preemption off
    batch_highwater: float = 0.9       # queue fraction that sheds batch tier
    autoscale: AutoscaleConfig = Field(default_factory=AutoscaleConfig)
    # straggler drain (round 15, runtime/straggler.py): with
    # straggler.enabled the FleetSupervisor runs the cross-rank
    # relative-slowness detector over the replicas' step_ms SERVE gauges
    # and DRAINS a verdicted replica through the existing death path —
    # admission stops, its lanes requeue exactly-once token-exact, the
    # replica restarts warmed, the strike counts toward blacklist_after
    # — instead of letting one throttled replica hold the shared
    # queue's p99 hostage. (abort_after is ignored fleet-side: the
    # drain IS the remediation.)
    straggler: StragglerConfig = Field(default_factory=StragglerConfig)


class ServingConfig(DeepSpeedConfigModel):
    """TPU-native (round 8): the continuous-batching serving loop
    (deepspeed_tpu/serving/, docs/SERVING.md). The KV cache is a paged
    POOL of ``pool_blocks`` blocks x ``block_size`` tokens shared by
    every in-flight sequence (block 0 reserved as the null block);
    requests are admitted FIFO when their lifetime block budget fits,
    prefilled into their blocks (reusing prefix-cached blocks for shared
    system prompts when ``prefix_cache``), and decoded by ONE fixed-shape
    jitted step over ``max_batch`` lanes. Pool HBM ≈ 2 (k+v) x layers x
    heads x head_dim x pool_blocks x block_size x dtype_bytes; size
    ``pool_blocks`` to the HBM left after weights. ``block_size`` trades
    fragmentation (last-block waste per sequence) against table length
    and prefix-cache granularity — shared prefixes are reused at
    full-block granularity only."""
    block_size: int = 32               # tokens per KV block
    pool_blocks: int = 256             # pool capacity incl. the null block
    max_batch: int = 8                 # decode lanes (fixed compiled shape)
    max_blocks_per_seq: int = 64       # table width; caps prompt+generation
    prefix_cache: bool = True          # reuse shared full-block prefixes
    max_queue: int = 4096              # admission queue bound (backpressure)
    kv_cache_dtype: Optional[str] = None   # None = model dtype; "int8" =
    #                                    quantized pool (round 12; round 17
    #                                    dequantizes IN the Pallas kernel)
    # weight-only blockwise int8 (round 17): "int8" packs the dense decode
    # kernels ONCE at engine construction into int8 + one f32 scale per
    # 256 contraction elements (quant_format's wire format) and routes
    # the decode matmuls through ops/pallas/quant_matmul — half the
    # weight HBM per token. None = serve the model dtype.
    weight_dtype: Optional[str] = None
    seed: int = 0                      # sampling PRNG seed
    # chunked prefill (round 12): > 0 advances a prompt's prefill at most
    # this many tokens per loop iteration, interleaved with decode steps
    # — a long prompt no longer adds head-of-line latency to running
    # lanes. 0 = whole prefill per admission (the round-8 behavior).
    # Token-exact vs whole prefill; compiles one extra prefill bucket at
    # most per chunk size (the chunk's block-rounded width).
    prefill_chunk_tokens: int = 0
    # per-lane top-k / top-p in the COMPILED decode step (round 12):
    # off by default because the nucleus filter puts a [B, V] sort in
    # every decode step; when off, submit(top_k=/top_p=) raises as
    # before. Parity with models.generation._sample is pinned by test.
    sampling_filters: bool = False
    # disaggregated serving (round 12): bound on finished-prefill items
    # waiting in the prefill->decode block-handoff queue (backpressure:
    # a full queue stalls prefill, never drops an item)
    handoff_queue: int = 16
    fleet: FleetConfig = Field(default_factory=FleetConfig)


class CommPlanConfig(DeepSpeedConfigModel):
    """TPU-native (round 10): the communication-planning subsystem
    (``deepspeed_tpu/comm_plan/``, docs/COMM.md). With ``enabled`` the
    engine resolves a wire format per collective site — the ZeRO-2
    gradient reduce-scatter and the MoE expert all-to-all — through the
    ladder override > recorded plan > size heuristic, and routes
    non-exact verdicts through the explicit blockwise-int8 collectives
    in ``runtime/comm/quantized.py``. ``plan_path`` points at a plan
    recorded by ``dstpu comm-plan sweep``; ``overrides`` forces an
    algorithm per site alias (``grad_reduce_scatter``,
    ``moe_all_to_all``) or wire kind (``reduce_scatter`` ...), and an
    unexecutable forced algorithm raises at initialize.
    ``guard_min_grad_norm`` is the accuracy guard: once the observed
    global grad norm drops below it, subsequent steps run the exact
    program (quantization error is no longer small relative to the
    signal; the latch applies to the LOSSY algorithms — ``overlap``
    moves exact values and is exempt); it costs the per-step metrics
    pull. ``quant_block`` is the elements-per-scale granularity of the
    int8 wire format (error is bounded by block absmax / 127 per
    element). Round 14: the ``overlap`` algorithm family (docs/COMM.md)
    — ``overlap_chunks`` is the pieces each overlapped collective is
    split into (chunk k+1's wire time hides under chunk k's compute; a
    static trace constant, so changing it recompiles once, never
    per-step), and ``overlap_min_leaf_elems`` keeps tiny param leaves
    on the implicit gather (chunking a bias buys nothing and costs a
    collective's latency floor per chunk)."""
    enabled: bool = False
    plan_path: Optional[str] = None
    overrides: Dict[str, str] = Field(default_factory=dict)
    quant_bits: int = 8
    quant_block: int = 256
    size_threshold_mb: float = 4.0     # heuristic regime boundary
    guard_min_grad_norm: float = 0.0   # 0 = guard off
    overlap_chunks: int = 4            # pieces per overlapped collective
    overlap_min_leaf_elems: int = 4096  # smaller leaves: implicit gather


class ProgressiveLayerDropConfig(DeepSpeedConfigModel):
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


class EigenvalueConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    max_iter: int = 100
    tol: float = 1e-2
    stability: float = 1e-6
    gas_boundary_resolution: int = 1
    layer_name: str = "bert.encoder.layer"
    layer_num: int = 0


class CompressionConfig(DeepSpeedConfigModel):
    """reference: compression/config.py — parsed; applied by compression/compress.py port."""
    weight_quantization: Dict[str, Any] = Field(default_factory=dict)
    activation_quantization: Dict[str, Any] = Field(default_factory=dict)
    sparse_pruning: Dict[str, Any] = Field(default_factory=dict)
    row_pruning: Dict[str, Any] = Field(default_factory=dict)
    head_pruning: Dict[str, Any] = Field(default_factory=dict)
    channel_pruning: Dict[str, Any] = Field(default_factory=dict)
    layer_reduction: Dict[str, Any] = Field(default_factory=dict)


class CurriculumLearningLegacyConfig(DeepSpeedConfigModel):
    enabled: bool = False
    curriculum_type: str = "seqlen"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    schedule_type: str = "fixed_linear"
    schedule_config: Dict[str, Any] = Field(default_factory=dict)


class DataEfficiencyConfig(DeepSpeedConfigModel):
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = Field(default_factory=dict)
    data_routing: Dict[str, Any] = Field(default_factory=dict)


class NebulaConfig(DeepSpeedConfigModel):
    enabled: bool = False
    persistent_storage_path: Optional[str] = None
    persistent_time_interval: int = 100
    num_of_version_in_retention: int = 2
    enable_nebula_load: bool = True


class OptimizerConfig(DeepSpeedConfigModel):
    type: str = "AdamW"
    params: Dict[str, Any] = Field(default_factory=dict)
    legacy_fusion: bool = False


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


# ---------------------------------------------------------------------------
# Top-level config
# ---------------------------------------------------------------------------

class DeepSpeedConfig(DeepSpeedConfigModel):
    """Parsed + validated ds_config with batch-size triangulation.

    reference: runtime/config.py:688+ (DeepSpeedConfig), including the
    train_batch = micro_batch * gradient_accumulation_steps * dp_world_size rule.
    """

    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None

    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None

    fp16: FP16Config = Field(default_factory=FP16Config)
    bf16: BF16Config = Field(default_factory=BF16Config)
    amp: AMPConfig = Field(default_factory=AMPConfig)

    zero_optimization: DeepSpeedZeroConfig = Field(default_factory=DeepSpeedZeroConfig)
    gradient_clipping: float = 0.0
    communication_data_type: Optional[str] = None
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    sparse_gradients: bool = False
    disable_allgather: bool = False

    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    dump_state: bool = False

    activation_checkpointing: ActivationCheckpointingConfig = Field(
        default_factory=ActivationCheckpointingConfig)
    sparse_attention: Optional[SparseAttentionConfig] = None
    flops_profiler: FlopsProfilerConfig = Field(default_factory=FlopsProfilerConfig)
    tensorboard: TensorBoardConfig = Field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = Field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = Field(default_factory=CSVConfig)
    comms_logger: CommsLoggerConfig = Field(default_factory=CommsLoggerConfig)
    aio: AIOConfig = Field(default_factory=AIOConfig)
    elasticity: ElasticityConfig = Field(default_factory=ElasticityConfig)
    autotuning: AutotuningConfig = Field(default_factory=AutotuningConfig)
    compression_training: CompressionConfig = Field(default_factory=CompressionConfig)
    data_efficiency: DataEfficiencyConfig = Field(default_factory=DataEfficiencyConfig)
    curriculum_learning: CurriculumLearningLegacyConfig = Field(
        default_factory=CurriculumLearningLegacyConfig)
    progressive_layer_drop: ProgressiveLayerDropConfig = Field(
        default_factory=ProgressiveLayerDropConfig)
    eigenvalue: EigenvalueConfig = Field(default_factory=EigenvalueConfig)
    quantize_training: Dict[str, Any] = Field(default_factory=dict)
    checkpoint: CheckpointConfig = Field(default_factory=CheckpointConfig)
    data_types: DataTypesConfig = Field(default_factory=DataTypesConfig)
    nonfinite_guard: NonFiniteGuardConfig = Field(
        default_factory=NonFiniteGuardConfig)
    integrity: IntegrityConfig = Field(default_factory=IntegrityConfig)
    watchdog: WatchdogConfig = Field(default_factory=WatchdogConfig)
    straggler: StragglerConfig = Field(default_factory=StragglerConfig)
    dataloader_drop_last: bool = False
    nebula: NebulaConfig = Field(default_factory=NebulaConfig)
    pipeline: PipelineConfig = Field(default_factory=PipelineConfig)
    serving: ServingConfig = Field(default_factory=ServingConfig)
    comm_plan: CommPlanConfig = Field(default_factory=CommPlanConfig)
    tensor_parallel: TensorParallelConfig = Field(default_factory=TensorParallelConfig)
    sequence_parallel: SequenceParallelConfig = Field(default_factory=SequenceParallelConfig)
    moe: MoEConfig = Field(default_factory=MoEConfig)

    zero_allow_untested_optimizer: bool = False
    gradient_accumulation_dtype: Optional[str] = None
    seed: int = 42

    # -- accessors matching reference engine property names ------------------

    @property
    def zero_enabled(self) -> bool:
        return self.zero_optimization.stage > 0

    @property
    def fp16_enabled(self) -> bool:
        return self.fp16.enabled

    @property
    def bfloat16_enabled(self) -> bool:
        return self.bf16.enabled

    @property
    def precision_dtype(self) -> str:
        if self.fp16.enabled:
            return "float16"
        if self.bf16.enabled:
            return "bfloat16"
        return "float32"

    @model_validator(mode="before")
    @classmethod
    def _aliases(cls, data):
        if isinstance(data, dict):
            if C.BF16_ALIAS in data and C.BF16 not in data:
                data[C.BF16] = data.pop(C.BF16_ALIAS)
        return data

    @model_validator(mode="after")
    def _fold_nonfinite_guard(self):
        """Deprecation shim (round 7): ``nonfinite_guard.abort_after``
        feeds the sentinel's single code path. An explicit
        ``integrity.nonfinite_abort_after`` wins over the alias."""
        if self.nonfinite_guard.abort_after > 0 and \
                self.integrity.nonfinite_abort_after == 0:
            self.integrity.nonfinite_abort_after = \
                self.nonfinite_guard.abort_after
        return self

    def resolve_batch_sizes(self, dp_world_size: int) -> None:
        """Batch-size triangulation: any 2 of 3 determine the third.

        reference: runtime/config.py _batch_assertion / _set_batch_related_parameters.
        """
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if tb is not None and mb is not None and gas is not None:
            pass
        elif tb is not None and mb is not None:
            gas = tb // (mb * dp_world_size)
            self.gradient_accumulation_steps = max(gas, 1)
        elif tb is not None and gas is not None:
            mb = tb // (gas * dp_world_size)
            self.train_micro_batch_size_per_gpu = max(mb, 1)
        elif mb is not None and gas is not None:
            self.train_batch_size = mb * gas * dp_world_size
        elif tb is not None:
            self.gradient_accumulation_steps = 1
            self.train_micro_batch_size_per_gpu = max(tb // dp_world_size, 1)
        elif mb is not None:
            self.gradient_accumulation_steps = 1
            self.train_batch_size = mb * dp_world_size
        else:
            raise ValueError(
                "At least one of train_batch_size / train_micro_batch_size_per_gpu "
                "must be set in the config")
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if tb != mb * gas * dp_world_size:
            raise ValueError(
                f"Batch size inconsistency: train_batch_size={tb} != "
                f"micro_batch({mb}) * gas({gas}) * dp_world_size({dp_world_size})")


def unconsumed_sections(cfg: "DeepSpeedConfig") -> List[str]:
    """Config sections the user activated but no engine code consumes yet.

    The reference errors on unimplemented features; we at least refuse to be
    silent (round-1 Weak #7: a user's ds_config 'worked' while doing nothing
    they asked). Update this list as subsystems land."""
    out = []
    if cfg.amp.enabled:
        out.append("amp (use bf16/fp16 sections instead)")
    if cfg.sparse_gradients:
        out.append("sparse_gradients")
    if cfg.nebula.enabled:
        out.append("nebula (use checkpoint.async_save)")
    if cfg.compression_training.layer_reduction.get("enabled"):
        out.append("compression_training.layer_reduction (apply explicitly "
                   "via compression.apply_layer_reduction)")
    if (cfg.data_efficiency.data_routing or {}).get(
            "random_ltd", {}).get("enabled"):
        out.append("data_efficiency.data_routing.random_ltd (set the model's "
                   "ltd_tokens/ltd_start/ltd_end config instead)")
    for key, sub in (cfg.data_efficiency.data_sampling or {}).items():
        # the engine consumes only the seqlen curriculum; any other enabled
        # sampling feature must not no-op silently
        if key != "curriculum_learning" and isinstance(sub, dict) \
                and sub.get("enabled"):
            out.append(f"data_efficiency.data_sampling.{key} "
                       "(use runtime.data_pipeline.DeepSpeedDataSampler)")
    return out


def warn_unconsumed(cfg: "DeepSpeedConfig") -> List[str]:
    secs = unconsumed_sections(cfg)
    if secs:
        from ..utils.logging import logger
        for s in secs:
            logger.warning(
                "ds_config section %r is parsed but NOT implemented by "
                "deepspeed_tpu — it will have no effect", s)
    return secs


def load_config(config: Union[str, dict, DeepSpeedConfig, None]) -> DeepSpeedConfig:
    """Accept a path to a JSON file, a dict, or an already-parsed config."""
    if config is None:
        return DeepSpeedConfig()
    if isinstance(config, DeepSpeedConfig):
        return config
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError(f"config must be a path, dict or DeepSpeedConfig, got {type(config)}")
    return DeepSpeedConfig(**config)
