from .config import (
    DeepSpeedConfig,
    DeepSpeedConfigModel,
    DeepSpeedZeroConfig,
    FP16Config,
    BF16Config,
    OptimizerConfig,
    SchedulerConfig,
    PipelineConfig,
    TensorParallelConfig,
    SequenceParallelConfig,
    MoEConfig,
    SparseAttentionConfig,
    load_config,
)
from . import constants
