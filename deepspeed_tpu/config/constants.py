"""ds_config.json key constants and defaults.

Mirrors the key surface of the reference's ``deepspeed/runtime/constants.py`` (see
SURVEY.md Appendix A) so that an unmodified DeepSpeed JSON config parses here.
"""

#########################################
# Batch size
#########################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

#########################################
# Optimizer / scheduler
#########################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE = "type"
OPTIMIZER_PARAMS = "params"
LEGACY_FUSION = "legacy_fusion"
SCHEDULER = "scheduler"
SCHEDULER_TYPE = "type"
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

# Optimizer type names accepted by the engine (reference engine.py:1042-1054)
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
ADAGRAD_OPTIMIZER = "adagrad"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, SGD_OPTIMIZER, ADAGRAD_OPTIMIZER,
]

#########################################
# Precision
#########################################
FP16 = "fp16"
BF16 = "bf16"
BF16_ALIAS = "bfloat16"
AMP = "amp"

#########################################
# Gradients / comm
#########################################
GRADIENT_CLIPPING = "gradient_clipping"
COMMUNICATION_DATA_TYPE = "communication_data_type"
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
SPARSE_GRADIENTS = "sparse_gradients"
DISABLE_ALLGATHER = "disable_allgather"

#########################################
# Sections
#########################################
ZERO_OPTIMIZATION = "zero_optimization"
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
SPARSE_ATTENTION = "sparse_attention"
FLOPS_PROFILER = "flops_profiler"
MONITOR_TENSORBOARD = "tensorboard"
MONITOR_WANDB = "wandb"
MONITOR_CSV = "csv_monitor"
COMMS_LOGGER = "comms_logger"
AIO = "aio"
ELASTICITY = "elasticity"
AUTOTUNING = "autotuning"
COMPRESSION_TRAINING = "compression_training"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
EIGENVALUE = "eigenvalue"
QUANTIZE_TRAINING = "quantize_training"
CHECKPOINT = "checkpoint"
DATA_TYPES = "data_types"
DATALOADER_DROP_LAST = "dataloader_drop_last"
NEBULA = "nebula"
PIPELINE = "pipeline"
TENSOR_PARALLEL = "tensor_parallel"
SEQUENCE_PARALLEL = "sequence_parallel"
MOE = "moe"

#########################################
# Logging / misc
#########################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
MEMORY_BREAKDOWN = "memory_breakdown"
DUMP_STATE = "dump_state"

#########################################
# Defaults
#########################################
TRAIN_BATCH_SIZE_DEFAULT = None
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None
GRADIENT_CLIPPING_DEFAULT = 0.0
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0
