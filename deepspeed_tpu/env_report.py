"""Environment / op-compatibility report — the reference's `ds_report` CLI
(env_report.py: op compat matrix + torch/cuda versions). TPU edition: jax
stack versions, device inventory, and a kernel-compatibility probe table
(each Pallas/collective family compile-checked on the current backend).
"""

from __future__ import annotations

import sys


GREEN_OK = "[OKAY]"
RED_NO = "[NO]"


def op_compat_table():
    """Probe each kernel family with a tiny compile (returns list of rows)."""
    import jax
    import jax.numpy as jnp
    rows = []

    def probe(name, fn):
        try:
            fn()
            rows.append((name, True, ""))
        except Exception as e:  # noqa: BLE001 — report, don't crash
            rows.append((name, False, type(e).__name__))

    x = jnp.ones((4, 4), jnp.float32)
    # graftlint: disable=TPU002 (one-shot diagnostic probe)
    probe("jit", lambda: jax.jit(lambda a: a @ a)(x).block_until_ready())

    def flash():
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
        q = jnp.ones((1, 1, 128, 32), jnp.float32)
        on_tpu = jax.default_backend() == "tpu"
        flash_attention(q, q, q, causal=True, interpret=not on_tpu
                        ).block_until_ready()
    probe("pallas_flash_attention", flash)

    def collectives():
        import numpy as np
        n = len(jax.devices())
        # graftlint: disable=TPU002 (one-shot diagnostic probe)
        jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(
            jnp.ones((n, 2))).block_until_ready()
    probe("collectives(psum)", collectives)

    def moe_gate():
        from deepspeed_tpu.moe import top1_gating
        top1_gating(jnp.ones((8, 2)), capacity=4)
    probe("moe_gating", moe_gate)
    return rows


def report_text() -> str:
    import jax
    import jaxlib
    lines = ["-" * 60, "deepspeed_tpu report", "-" * 60]
    import deepspeed_tpu
    lines.append(f"deepspeed_tpu ........ {deepspeed_tpu.__version__}")
    lines.append(f"jax .................. {jax.__version__}")
    lines.append(f"jaxlib ............... {jaxlib.__version__}")
    try:
        import flax
        lines.append(f"flax ................. {flax.__version__}")
    except ImportError:
        lines.append("flax ................. not installed")
    lines.append(f"python ............... {sys.version.split()[0]}")
    lines.append(f"backend .............. {jax.default_backend()}")
    devs = jax.devices()
    lines.append(f"devices .............. {len(devs)} x {devs[0].device_kind}")
    lines.append("-" * 60)
    lines.append("kernel/op compatibility")
    for name, ok, err in op_compat_table():
        status = GREEN_OK if ok else f"{RED_NO} ({err})"
        lines.append(f"  {name:<28s} {status}")
    lines.append("-" * 60)
    lines.append("op registry (impl selection; reference: op_builder/ALL_OPS)")
    from deepspeed_tpu.ops.registry import compatibility_report
    for op, impls in compatibility_report().items():
        for impl, ok in impls.items():
            status = GREEN_OK if ok else RED_NO
            lines.append(f"  {op + '/' + impl:<28s} {status}")
    lines.append("-" * 60)
    return "\n".join(lines)


def cli_main():
    print(report_text())


if __name__ == "__main__":
    cli_main()
