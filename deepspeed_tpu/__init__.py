"""deepspeed_tpu — a TPU-native training/inference optimization framework.

A from-scratch rebuild of the capabilities of DeepSpeed (reference v0.8.1)
on jax/XLA/pjit/shard_map/Pallas. Public surface mirrors the reference's
``deepspeed/__init__.py:14-36``: ``initialize``, ``init_inference``,
``add_config_arguments``, ``init_distributed``, ``DeepSpeedConfig``, ``zero``.
"""

from typing import Optional, Tuple

# forward-compat (OPT-IN): the package targets the modern `jax.shard_map`
# entry point; older jax (the 0.4.x line) only ships
# jax.experimental.shard_map with different kwargs. DSTPU_JAX_COMPAT=1
# installs an adapter before any submodule imports. Off by default: on
# the 0.4.x jaxlib the adapter unlocks compile paths (qwZ+TP, SPMD
# pipeline) that ABORT inside XLA — a clean trace-time AttributeError is
# strictly safer than a compiler crash taking down the process.
import os as _os
if _os.environ.get("DSTPU_JAX_COMPAT") == "1":
    from .utils.jax_compat import install_shard_map_compat as _ism
    _ism()

from .version import __version__
from .config import DeepSpeedConfig, load_config
from . import comm
from .comm import init_distributed
from .parallel.mesh import MeshManager, build_mesh_from_config, get_global_mesh
from .parallel.topology import (
    ProcessTopology,
    PipeDataParallelTopology,
    PipeModelDataParallelTopology,
    PipelineParallelGrid,
)
from .runtime.engine import DeepSpeedEngine
from .runtime import act_checkpoint as checkpointing  # deepspeed.checkpointing parity
from .runtime.lr_schedules import LRScheduler, build_schedule


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required: Optional[bool] = None,
               collate_fn=None,
               config=None,
               config_params=None,
               **kwargs) -> Tuple:
    """Wrap a model in a DeepSpeedEngine.

    Signature parity with the reference ``deepspeed.initialize``
    (deepspeed/__init__.py:52-156); returns (engine, optimizer, dataloader,
    lr_scheduler). TPU-specific extras are keyword-only: ``loss_fn``,
    ``apply_fn``, ``example_batch``, ``rng``, ``sharding_rules``,
    ``mesh_manager``.
    """
    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if dist_init_required is None or dist_init_required:
        init_distributed()

    from .models.pipeline import PipelinedTransformer
    from .runtime.pipe.engine import PipelineEngine
    engine_cls = (PipelineEngine if isinstance(model, PipelinedTransformer)
                  else DeepSpeedEngine)
    engine = engine_cls(
        model=model,
        config=config,
        model_parameters=model_parameters,
        optimizer=optimizer,
        lr_scheduler=lr_scheduler,
        mpu=mpu,
        **kwargs)

    dataloader = None
    if training_data is not None:
        from .runtime.dataloader import DeepSpeedDataLoader
        dataloader = DeepSpeedDataLoader(
            training_data,
            batch_size=engine.config.train_batch_size,
            collate_fn=collate_fn,
            drop_last=engine.config.dataloader_drop_last)

    return engine, engine.optimizer, dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """reference: deepspeed/__init__.py:233 — build an InferenceEngine."""
    from .inference.engine import InferenceEngine
    return InferenceEngine(model=model, config=config, **kwargs)


def add_config_arguments(parser):
    """reference: deepspeed/__init__.py:159-223 — argparse flags."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the DeepSpeed json configuration")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse_suppress())
    return parser


def argparse_suppress():
    import argparse
    return argparse.SUPPRESS




def default_inference_config():
    """reference: deepspeed/__init__.py:246 — the default inference config
    as a plain dict (feed it back to init_inference after edits)."""
    from .inference.config import DeepSpeedInferenceConfig
    return DeepSpeedInferenceConfig().model_dump()


from .models.transformer import (  # noqa: E402  (reference export names)
    DeepSpeedTransformerLayer, DeepSpeedTransformerConfig)
from .models.hf import (  # noqa: E402
    replace_transformer_layer, revert_transformer_layer)

from . import zero  # noqa: E402  (re-export; depends on runtime)
