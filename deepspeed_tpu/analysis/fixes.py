"""graftlint autofixes (``--fix``) for the mechanical rules.

Only rules whose fix is a pure syntactic rewrite with exactly one right
answer are fixable — the analyzer must never guess at semantics:

TPU008  PartitionSpec canonicalization: drop trailing ``None`` entries,
        unwrap single-name tuples, rewrite empty-tuple entries to
        ``None`` — producing the compiler's canonical form, which is the
        whole point of the rule.
TPU009  scan-carry cast-back: wrap the widened carry expression in
        ``.astype(<init dtype>)`` — the init's own 16-bit dtype token is
        the one right answer (the carry dtype must be invariant across
        iterations), and the f32 math INSIDE the expression is preserved
        (accumulate in an f32 island, carry 16-bit — the rule's
        recommended idiom).
TPU010  wrap the statement launching ``pl.pallas_call`` in
        ``with jax.named_scope("<enclosing-fn>"):`` (adding ``import
        jax`` when the module lacks it).
TPU019  thread ``lock_timeout=5.0`` through a bounded-lock API call on
        an exit path — the API already defines the parameter with the
        right semantics (None = block forever), so passing it is the
        one right answer; 5.0 matches the watchdog's
        ``_STAMP_LOCK_TIMEOUT`` convention. Only the
        missing-``lock_timeout`` findings are fixable; raw
        ``with``/``acquire`` sites change control flow and stay manual.
TPU021  swap a hardcoded exit-code literal for its named constant and
        import it from ``deepspeed_tpu.exit_codes`` when the module
        doesn't already bind the name.

Fixes are applied as source-span edits computed from the parsed AST.
Within one round, overlapping edits are dropped (outermost wins) and the
CLI re-lints + re-fixes until a round applies nothing — which also makes
``--fix`` idempotent by construction: a fixed file produces no findings,
so a second run edits nothing.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .core import Finding, ModuleInfo

#: rules --fix knows how to rewrite
FIXABLE = ("TPU008", "TPU009", "TPU010", "TPU019", "TPU021")


class Edit:
    """Replace source[start:end) (character offsets) with ``text``."""

    __slots__ = ("start", "end", "text")

    def __init__(self, start: int, end: int, text: str):
        self.start = start
        self.end = end
        self.text = text


def _offsets(source: str) -> List[int]:
    """Char offset of the start of each 1-indexed line."""
    offs = [0]
    for line in source.splitlines(keepends=True):
        offs.append(offs[-1] + len(line))
    return offs


def _span(source: str, offs: List[int], node: ast.AST) -> Tuple[int, int]:
    start = offs[node.lineno - 1] + node.col_offset
    end = offs[node.end_lineno - 1] + node.end_col_offset
    return start, end


def _seg(source: str, node: ast.AST) -> str:
    return ast.get_source_segment(source, node) or ast.unparse(node)


# ------------------------------------------------------------------ TPU008

def _fix_spec(module: ModuleInfo, call: ast.Call,
              offs: List[int]) -> Optional[Edit]:
    """Canonicalize a P(...) literal in place."""
    if call.keywords:
        return None                 # unusual spelling: leave it alone
    src = module.source
    args: List[str] = []
    for a in call.args:
        if isinstance(a, ast.Tuple) and len(a.elts) == 1:
            args.append(_seg(src, a.elts[0]))
        elif isinstance(a, ast.Tuple) and not a.elts:
            args.append("None")
        else:
            args.append(_seg(src, a))
    while args and args[-1] == "None":
        args.pop()
    new = f"{_seg(src, call.func)}({', '.join(args)})"
    start, end = _span(src, offs, call)
    if src[start:end] == new:
        return None
    return Edit(start, end, new)


# ------------------------------------------------------------------ TPU009

def _half_token(module: ModuleInfo, call: ast.Call,
                init: ast.AST) -> Optional[str]:
    """The init expression's own 16-bit dtype spelled as source — the one
    right answer for the cast-back (following a plain init name to its
    assignments in the function enclosing the scan, exactly the dataflow
    the rule used to decide the init is 16-bit)."""
    from .rules import _HALF_NAMES, _qual

    def scan_expr(expr: ast.AST) -> Optional[str]:
        for n in ast.walk(expr):
            if isinstance(n, (ast.Attribute, ast.Name)) and \
                    _qual(module, n) in _HALF_NAMES:
                return _seg(module.source, n)
            if isinstance(n, ast.Constant) and n.value in ("bfloat16",
                                                           "float16"):
                return repr(n.value)
        return None

    tok = scan_expr(init)
    if tok is not None or not isinstance(init, ast.Name):
        return tok
    encl = module.enclosing_function(call)
    if encl is None:
        return None
    for node in module.fn_nodes(encl):
        if isinstance(node, ast.Assign) and any(
                isinstance(leaf, ast.Name) and leaf.id == init.id
                for t in node.targets for leaf in ast.walk(t)):
            tok = scan_expr(node.value)
            if tok is not None:
                return tok
    return None


def _tpu009_contexts(module: ModuleInfo) -> Dict[int, Tuple[ast.AST, str]]:
    """``id(widening-cast node)`` -> (carry expression containing it,
    init dtype token) for every TPU009-shaped scan site. The finding
    anchors on the CAST (the precise squiggle for the report), but the
    rewrite wraps the WHOLE carry expression — preserving any f32 math
    inside it as an island — so the fixer re-walks the rule's dataflow to
    recover that enclosing expression."""
    from .rules import ScanCarryWideningRule, _qual
    rule = ScanCarryWideningRule()
    out: Dict[int, Tuple[ast.AST, str]] = {}
    for call in module.all_calls:
        if _qual(module, call.func) not in rule._SCANS or not call.args:
            continue
        init = (call.args[1] if len(call.args) >= 2 else
                next((kw.value for kw in call.keywords
                      if kw.arg == "init"), None))
        if init is None or not rule._init_halfish(module, call, init):
            continue
        token = _half_token(module, call, init)
        if token is None:
            continue
        body = module.scope.resolve_local_def(call.args[0]) \
            if isinstance(call.args[0], ast.Name) else call.args[0]
        if not isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            continue
        for carry in rule._carry_exprs(module, body):
            wide = rule._widening_cast(module, carry)
            if wide is None or rule._narrows_back(module, carry):
                continue
            out[id(wide)] = (carry, token)
            break               # one finding per scan site, same as the rule
    return out


def _fix_cast_back(module: ModuleInfo, carry: ast.AST, token: str,
                   offs: List[int]) -> Optional[Edit]:
    """Append ``.astype(<init dtype>)`` to the carry expression. An atom
    (name/call/attribute/subscript) chains directly; anything else is
    parenthesized first."""
    src = module.source
    seg = _seg(src, carry)
    atom = isinstance(carry, (ast.Name, ast.Attribute, ast.Call,
                              ast.Subscript))
    new = f"{seg}.astype({token})" if atom else f"({seg}).astype({token})"
    start, end = _span(src, offs, carry)
    if src[start:end] == new:
        return None
    return Edit(start, end, new)


# ------------------------------------------------------------------ TPU010

def _enclosing_stmt(module: ModuleInfo, node: ast.AST) -> Optional[ast.stmt]:
    cur: Optional[ast.AST] = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = module.parent(cur)
    return cur


def _fix_named_scope(module: ModuleInfo, call: ast.Call,
                     offs: List[int]) -> Optional[Edit]:
    """Indent the launching statement under a named_scope ``with``."""
    stmt = _enclosing_stmt(module, call)
    if stmt is None:
        return None
    src = module.source
    first = module.lines[stmt.lineno - 1]
    indent = first[:len(first) - len(first.lstrip())]
    fn = module.enclosing_function(call)
    name = getattr(fn, "name", None) or "pallas_kernel"
    body = [f"{indent}with jax.named_scope(\"{name}\"):"]
    for ln in range(stmt.lineno, stmt.end_lineno + 1):
        body.append("    " + module.lines[ln - 1])
    start = offs[stmt.lineno - 1]
    end = offs[stmt.end_lineno - 1] + len(module.lines[stmt.end_lineno - 1])
    return Edit(start, end, "\n".join(body))


def _needs_jax_import(module: ModuleInfo) -> bool:
    return module.scope.imports.aliases.get("jax") != "jax"


def _import_jax_edit(module: ModuleInfo, offs: List[int]) -> Edit:
    """Insert ``import jax`` after the last top-level import (or at the
    top, past a module docstring)."""
    line = 0
    for node in module.tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            line = max(line, node.end_lineno)
        elif line == 0 and isinstance(node, ast.Expr) and isinstance(
                node.value, ast.Constant) and isinstance(
                node.value.value, str):
            line = node.end_lineno      # docstring
    pos = offs[line] if line < len(offs) else len(module.source)
    return Edit(pos, pos, "import jax\n")


# ------------------------------------------------------------------ TPU019

def _fix_lock_timeout(module: ModuleInfo, call: ast.Call,
                      offs: List[int]) -> Optional[Edit]:
    """Append ``lock_timeout=5.0`` to a bounded-lock API call. The rule
    only anchors on calls whose resolved target defines the parameter
    and that don't already pass it, so appending is always valid."""
    if any(kw.arg == "lock_timeout" for kw in call.keywords):
        return None                 # already fixed (stale finding)
    src = module.source
    start, end = _span(src, offs, call)
    seg = src[start:end]
    if not seg.endswith(")"):
        return None                 # parenthesized oddity: leave it
    inner = seg[len(_seg(src, call.func)):].strip()
    empty = inner in ("()", "( )")
    text = "lock_timeout=5.0)" if empty else ", lock_timeout=5.0)"
    return Edit(end - 1, end, text)


# ------------------------------------------------------------------ TPU021

def _fix_exit_code(module: ModuleInfo, node: ast.Constant,
                   offs: List[int]) -> Optional[Tuple[Edit, Optional[str]]]:
    """Replace the literal with its constant name; also report the name
    to import when the module doesn't already bind it."""
    from .rules_concurrency import ExitCodeLiteralRule
    name = ExitCodeLiteralRule.BY_VALUE.get(node.value)
    if name is None:
        return None
    start, end = _span(module.source, offs, node)
    bound = name in module.scope.imports.aliases or any(
        isinstance(n, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in n.targets)
        for n in module.nodes_by_fn.get(None, ()))
    return Edit(start, end, name), (None if bound else name)


def _import_names_edit(module: ModuleInfo, offs: List[int],
                       names: List[str]) -> Edit:
    """Insert a ``from deepspeed_tpu.exit_codes import ...`` after the
    last top-level import (same placement logic as the jax import)."""
    line = 0
    for n in module.tree.body:
        if isinstance(n, (ast.Import, ast.ImportFrom)):
            line = max(line, n.end_lineno)
        elif line == 0 and isinstance(n, ast.Expr) and isinstance(
                n.value, ast.Constant) and isinstance(n.value.value, str):
            line = n.end_lineno          # docstring
    pos = offs[line] if line < len(offs) else len(module.source)
    stmt = f"from deepspeed_tpu.exit_codes import " \
           f"{', '.join(sorted(set(names)))}\n"
    return Edit(pos, pos, stmt)


# ------------------------------------------------------------------ driver

def compute_edits(module: ModuleInfo,
                  findings: List[Finding]) -> List[Edit]:
    """One round of non-overlapping edits for this module's fixable
    findings. Overlaps (a P-literal inside a statement being wrapped)
    resolve outermost-first; the CLI's fix loop picks up the rest on the
    next round."""
    offs = _offsets(module.source)
    edits: List[Edit] = []
    wrapped_stmts = set()
    want_jax_import = False
    want_exit_names: List[str] = []
    tpu009_ctx: Optional[Dict[int, Tuple[ast.AST, str]]] = None
    for f in findings:
        if f.node is None:
            continue
        if f.rule == "TPU008":
            # cross-module constant findings anchor on the USE name, not
            # a P(...) call — only literal/same-module-constant findings
            # (whose node IS the call) are mechanically fixable
            if not isinstance(f.node, ast.Call):
                continue
            e = _fix_spec(module, f.node, offs)
            if e:
                edits.append(e)
        elif f.rule == "TPU009":
            if tpu009_ctx is None:
                tpu009_ctx = _tpu009_contexts(module)
            ctx = tpu009_ctx.get(id(f.node))
            if ctx:
                e = _fix_cast_back(module, ctx[0], ctx[1], offs)
                if e:
                    edits.append(e)
        elif f.rule == "TPU010":
            stmt = _enclosing_stmt(module, f.node)
            if stmt is None or id(stmt) in wrapped_stmts:
                continue
            e = _fix_named_scope(module, f.node, offs)
            if e:
                wrapped_stmts.add(id(stmt))
                edits.append(e)
                want_jax_import = _needs_jax_import(module) or want_jax_import
        elif f.rule == "TPU019":
            # only the missing-lock_timeout findings anchor on a Call
            # (with/acquire sites are report-only by design)
            if isinstance(f.node, ast.Call):
                e = _fix_lock_timeout(module, f.node, offs)
                if e:
                    edits.append(e)
        elif f.rule == "TPU021":
            if isinstance(f.node, ast.Constant):
                res = _fix_exit_code(module, f.node, offs)
                if res:
                    edits.append(res[0])
                    if res[1]:
                        want_exit_names.append(res[1])
    if want_jax_import:
        edits.append(_import_jax_edit(module, offs))
    if want_exit_names:
        edits.append(_import_names_edit(module, offs, want_exit_names))
    # outermost-first on overlap: sort by (start, -end) and drop any edit
    # that overlaps one already kept
    edits.sort(key=lambda e: (e.start, -e.end))
    kept: List[Edit] = []
    for e in edits:
        if any(e.start < k.end and k.start < e.end for k in kept):
            continue
        kept.append(e)
    return kept


def apply_edits(source: str, edits: List[Edit]) -> str:
    for e in sorted(edits, key=lambda e: e.start, reverse=True):
        source = source[:e.start] + e.text + source[e.end:]
    return source


def fix_paths(paths, select=None, ignore=None, root=None,
              baseline_path: Optional[str] = None,
              max_rounds: int = 5) -> Tuple[int, List[str]]:
    """Lint/fix/re-lint until a round applies nothing. Returns (#edits
    applied, sorted changed file paths). Suppressed and baselined
    findings are the author's recorded judgment and are left untouched."""
    from .baseline import Baseline
    from .core import lint_modules
    total = 0
    changed: Dict[str, bool] = {}
    for _ in range(max_rounds):
        findings, modules = lint_modules(paths, select=select,
                                         ignore=ignore, root=root)
        if baseline_path:
            Baseline.load(baseline_path).apply(findings)
        by_path: Dict[str, List[Finding]] = {}
        for f in findings:
            if f.rule in FIXABLE and not f.suppressed and not f.baselined:
                by_path.setdefault(f.path, []).append(f)
        if not by_path:
            break
        applied_this_round = 0
        for module in modules:
            todo = by_path.get(module.rel_path)
            if not todo:
                continue
            edits = compute_edits(module, todo)
            if not edits:
                continue
            new_source = apply_edits(module.source, edits)
            try:
                ast.parse(new_source)
            except SyntaxError:     # never write a file we broke
                continue
            with open(module.path, "w", encoding="utf-8") as fh:
                fh.write(new_source)
            changed[module.path] = True
            applied_this_round += len(edits)
        total += applied_this_round
        if not applied_this_round:
            break
    return total, sorted(changed)
