"""graftlint rules TPU001–TPU010, TPU014 and TPU015 (TPU011–013 live in
rules_collective.py).

Each rule targets one class of bug that regresses the gas-amortized train
step silently: the bench still runs, just slower (host syncs, retraces)
or subtly wrong (dtype leaks, key reuse). Rules lean on the per-module
JitScope (see jitscope.py) to know which code runs under a trace and
which code is the host-side step path.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Set

from .core import Finding, ModuleInfo, Rule, Severity, register

# identifiers that smell like device values when they appear inside a
# float()/int()/bool() pull on the host step path
_DEVICEISH = re.compile(
    r"loss|grad|norm|metric|logit|scale|overflow|state|tensor|array", re.I)

_F64_NAMES = {"jax.numpy.float64", "numpy.float64", "jax.numpy.complex128",
              "numpy.complex128"}
_F32_NAMES = {"jax.numpy.float32", "numpy.float32"}
_HALF_NAMES = {"jax.numpy.bfloat16", "jax.numpy.float16",
               "numpy.float16", "ml_dtypes.bfloat16"}


def _qual(module: ModuleInfo, node: ast.AST) -> Optional[str]:
    return module.scope.imports.qualify(node)


def _is_literal(node: ast.AST) -> bool:
    return isinstance(node, (ast.Constant, ast.JoinedStr)) or (
        isinstance(node, (ast.Tuple, ast.List))
        and all(_is_literal(e) for e in node.elts)) or (
        isinstance(node, ast.UnaryOp) and _is_literal(node.operand))


def _mentions_deviceish(module: ModuleInfo, node: ast.AST) -> bool:
    """Does the expression reference something that plausibly lives on
    device — a jnp/jax call (other than device_get) or an identifier /
    string key matching the device-ish vocabulary?"""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            q = _qual(module, n.func)
            if q and q.startswith(("jax.numpy.", "jax.lax.")):
                return True
            if q and q.startswith("jax.") and not q.endswith("device_get"):
                return True
        elif isinstance(n, ast.Name) and _DEVICEISH.search(n.id):
            return True
        elif isinstance(n, ast.Attribute) and _DEVICEISH.search(n.attr):
            return True
        elif isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and _DEVICEISH.search(n.value):
            return True
    return False


def _walk_functions(module: ModuleInfo, traced_only: bool = True):
    for fn in module.scope._defs:
        if traced_only and not module.scope.fn_traced(fn):
            continue
        yield fn


@register
class HostSyncRule(Rule):
    """TPU001 — host↔device synchronization in a jitted or step path.

    Inside traced code any host pull (.item(), float(tracer),
    np.asarray(tracer), device_get, .tolist(), block_until_ready) either
    fails at trace time on a rarely-exercised branch or, worse, silently
    constant-folds a value that should be dynamic. On the host step path
    (train_batch/step/forward/backward or ``# graftlint: hotpath``), an
    implicit pull stalls async dispatch — the exact overhead gas
    amortization exists to hide. Explicit ``jax.device_get`` on the step
    path is the sanctioned idiom (one acknowledged transfer) and is not
    flagged there.
    """

    code = "TPU001"
    name = "host-sync"
    severity = Severity.ERROR
    summary = "host-device sync in a jitted/step path"

    _SYNC_METHODS = {"item", "tolist", "block_until_ready"}
    _NP_PULLS = {"numpy.asarray", "numpy.array", "numpy.float32",
                 "numpy.float64", "numpy.int32", "numpy.int64"}
    _CASTS = {"float", "int", "bool"}

    @staticmethod
    def _host_names(module: ModuleInfo, fn) -> Set[str]:
        """Locals assigned from jax.device_get(...) — already host-side, so
        casting them is free."""
        names: Set[str] = set()
        if fn is None:
            return names
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and _qual(
                    module, node.value.func) == "jax.device_get":
                for t in node.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            names.add(leaf.id)
        return names

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        scope = module.scope
        host_names_cache = {}
        for node in module.all_calls:
            traced = scope.in_traced(node)
            hot = scope.in_hot(node)
            if not traced and not hot:
                continue
            sev = Severity.ERROR if traced else Severity.WARNING
            where = "traced code" if traced else "the host step path"
            f = node.func
            # .item() / .tolist() / .block_until_ready()
            if isinstance(f, ast.Attribute) and f.attr in self._SYNC_METHODS \
                    and not node.args:
                yield self.finding(
                    module, node,
                    f".{f.attr}() forces a device sync in {where}",
                    severity=sev)
                continue
            q = _qual(module, f)
            # np.asarray / np.array on a non-literal in traced/hot code
            if q in self._NP_PULLS and node.args \
                    and not _is_literal(node.args[0]) \
                    and (traced or _mentions_deviceish(module, node.args[0])):
                yield self.finding(
                    module, node,
                    f"{q}(...) materializes a device value on host in "
                    f"{where}; keep math in jnp or device_get explicitly",
                    severity=sev)
                continue
            # device_get / block_until_ready inside traced code only
            if traced and q in ("jax.device_get", "jax.block_until_ready"):
                yield self.finding(
                    module, node,
                    f"{q} inside traced code breaks the trace "
                    "(move it outside the compiled step)", severity=sev)
                continue
            # float()/int()/bool() pulls — device-ish evidence required in
            # both tiers (casting a closed-over python int under trace is
            # harmless; casting anything named loss/grad/norm/... is not)
            if isinstance(f, ast.Name) and f.id in self._CASTS \
                    and len(node.args) == 1:
                arg = node.args[0]
                if _is_literal(arg):
                    continue
                if isinstance(arg, ast.Call):
                    aq = _qual(module, arg.func)
                    if aq in ("jax.device_get", "len", "float", "int",
                              "numpy.prod", "math.prod"):
                        continue
                encl = module.enclosing_function(node)
                if encl not in host_names_cache:
                    host_names_cache[encl] = self._host_names(module, encl)
                base = arg
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if isinstance(base, ast.Name) and \
                        base.id in host_names_cache[encl]:
                    continue
                if _mentions_deviceish(module, arg):
                    yield self.finding(
                        module, node,
                        f"{f.id}(...) blocks on a device value in {where}; "
                        "batch scalars into one jax.device_get",
                        severity=sev)


@register
class RetraceRule(Rule):
    """TPU002 — retrace risk: jit wrappers rebuilt per call.

    ``jax.jit`` keyed by function object identity: constructing the wrapper
    inside a loop (or constructing-and-immediately-calling it inside any
    function) makes every execution a cache miss — a full retrace+compile
    that shows up as a multi-second stall per step instead of a bench
    number.
    """

    code = "TPU002"
    name = "retrace-risk"
    severity = Severity.ERROR
    summary = "jit wrapper constructed per call (retrace risk)"

    def _fresh_object(self, module: ModuleInfo, node: ast.Call) -> bool:
        """Is the wrapped callable a fresh object on every execution of
        this line? jit's trace cache is keyed by function identity:
        module-level defs are stable (measured: 1 trace across repeated
        ``jax.jit(f)(x)``), while lambdas, bound-method attribute reads
        and nested closures produce a new object — and a retrace — per
        pass."""
        if not node.args:
            return False
        arg = node.args[0]
        if isinstance(arg, (ast.Lambda, ast.Attribute, ast.Call)):
            return True
        if isinstance(arg, ast.Name):
            target = module.scope.resolve_local_def(arg)
            if target is None:
                return True     # unresolved (e.g. a function parameter)
            # nested def => closure rebuilt per call of the enclosing fn
            return module.enclosing_function(target) is not None
        return False

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        scope = module.scope
        for node in module.all_calls:
            if not scope.is_jit_call(node):
                continue
            if not self._fresh_object(module, node):
                continue
            # under an outer trace the inner jit is inlined once per outer
            # trace — not a per-step cost
            if scope.in_traced(node):
                continue
            # (a) jit(<fresh fn>) under a loop
            cur = module.parent(node)
            in_loop = False
            while cur is not None and not isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                    in_loop = True
                    break
                cur = module.parent(cur)
            if in_loop:
                yield self.finding(
                    module, node,
                    "jit over a per-iteration callable inside a loop: "
                    "every iteration is a fresh trace cache")
            else:
                # (b) jit(<fresh fn>)(args) immediately invoked inside a
                # function — a second pass through this code retraces
                parent = module.parent(node)
                if isinstance(parent, ast.Call) and parent.func is node \
                        and module.enclosing_function(node) is not None:
                    yield self.finding(
                        module, node,
                        "jit-then-call over a lambda/bound-method/closure "
                        "retraces on every pass; hoist a stable jitted "
                        "callable", severity=Severity.WARNING)
            # (c) unhashable static default: list/dict/set passed to a
            # static arg in the wrapper call
            for kw in node.keywords:
                if kw.arg in ("static_argnums", "static_argnames"):
                    continue
                if isinstance(kw.value, (ast.List, ast.Dict, ast.Set)):
                    yield self.finding(
                        module, kw.value,
                        f"unhashable {type(kw.value).__name__.lower()} "
                        f"literal for jit option '{kw.arg}' defeats the "
                        "jit cache", severity=Severity.WARNING)


@register
class ImpureJitRule(Rule):
    """TPU003 — side effects inside traced functions.

    A traced function runs ONCE at trace time; ``self.x = ...``, ``global``
    writes, or mutating a closed-over container happen during tracing and
    never again — the classic "my counter only incremented once" bug, or a
    silent leak of tracers into host state that poisons later steps.
    """

    code = "TPU003"
    name = "impure-jit"
    severity = Severity.ERROR
    summary = "mutation of external state under trace"

    _MUTATORS = {"append", "extend", "add", "update", "insert", "pop",
                 "setdefault", "remove", "clear"}

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        scope = module.scope
        for fn in _walk_functions(module, traced_only=True):
            # names local to this fn or to a traced ancestor are
            # trace-local — mutating them is not a side effect
            local_names = _local_names(module, fn)
            anc = module.enclosing_function(fn)
            while anc is not None:
                if scope.fn_traced(anc):
                    local_names |= _local_names(module, anc)
                anc = module.enclosing_function(anc)
            for node in module.fn_nodes(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Attribute) and isinstance(
                                    leaf.value, ast.Name) and \
                                    leaf.value.id == "self":
                                yield self.finding(
                                    module, node,
                                    f"assignment to self.{leaf.attr} inside "
                                    "a traced function runs once at trace "
                                    "time, not per step")
                elif isinstance(node, ast.Global):
                    yield self.finding(
                        module, node,
                        "'global' write inside a traced function is a "
                        "trace-time side effect")
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) and \
                        node.func.attr in self._MUTATORS and isinstance(
                        node.func.value, ast.Name) and \
                        node.func.value.id not in local_names:
                    yield self.finding(
                        module, node,
                        f"mutating closed-over '{node.func.value.id}."
                        f"{node.func.attr}(...)' inside a traced function "
                        "is a trace-time side effect",
                        severity=Severity.WARNING)


def _local_names(module: ModuleInfo, fn: ast.AST) -> Set[str]:
    names: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in (args.args + args.posonlyargs + args.kwonlyargs):
            names.add(a.arg)
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
    for node in module.fn_nodes(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        names.add(leaf.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.For)):
            t = node.target
            for leaf in ast.walk(t):
                if isinstance(leaf, ast.Name):
                    names.add(leaf.id)
        elif isinstance(node, (ast.withitem,)) and node.optional_vars:
            for leaf in ast.walk(node.optional_vars):
                if isinstance(leaf, ast.Name):
                    names.add(leaf.id)
    return names


@register
class DtypeDisciplineRule(Rule):
    """TPU004 — dtype discipline in the bf16 hot path.

    f64 anywhere under trace is either silently demoted (x64 off) or a
    catastrophic MXU bypass; downcasting losses/logits to 16-bit destroys
    the numerics headroom the fp32-softmax/fp32-loss convention exists
    for. Explicit f32 scalar construction in traced code is reported at
    INFO level — an f32-typed scalar upcasts every bf16 operand it
    touches, but intentional f32 islands (grad norms, loss) are common
    and correct.
    """

    code = "TPU004"
    name = "dtype-discipline"
    severity = Severity.ERROR
    summary = "f64 under trace / loss-logit downcast / f32 scalar leak"

    _LOSSY = re.compile(r"loss|logit", re.I)

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        scope = module.scope
        for node in module.all_nodes:
            if not scope.in_traced(node):
                continue
            q = _qual(module, node) if isinstance(
                node, (ast.Attribute, ast.Name)) else None
            if q in _F64_NAMES:
                yield self.finding(
                    module, node,
                    f"{q} under trace: f64 is demoted (jax_enable_x64 off) "
                    "or falls off the MXU; use f32/bf16")
                continue
            if isinstance(node, ast.Constant) and node.value in (
                    "float64", "complex128") and scope.in_traced(node):
                yield self.finding(
                    module, node,
                    "dtype string 'float64' under trace; use f32/bf16")
                continue
            if not isinstance(node, ast.Call):
                continue
            # x.astype(half) / jnp.asarray(x, half) on loss/logit values
            half_target = None
            value_expr = None
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "astype" and node.args:
                dq = _qual(module, node.args[0])
                if dq in _HALF_NAMES or (
                        isinstance(node.args[0], ast.Constant)
                        and node.args[0].value in ("bfloat16", "float16")):
                    half_target = dq or node.args[0].value
                    value_expr = node.func.value
            elif _qual(module, node.func) in _HALF_NAMES and node.args:
                half_target = _qual(module, node.func)
                value_expr = node.args[0]
            if half_target is not None and value_expr is not None:
                src = ast.unparse(value_expr)
                if self._LOSSY.search(src):
                    yield self.finding(
                        module, node,
                        f"downcast of '{src}' to 16-bit: losses/logits "
                        "must stay f32 (softmax/CE numerics)",
                        severity=Severity.WARNING)
                continue
            # f32 scalar construction (INFO): upcasts bf16 operands
            fq = _qual(module, node.func)
            if fq in _F32_NAMES and node.args and _is_literal(node.args[0]):
                yield self.finding(
                    module, node,
                    f"{fq} scalar under trace upcasts bf16 operands; a "
                    "weak Python scalar keeps the compute dtype",
                    severity=Severity.INFO)


@register
class DonationRule(Rule):
    """TPU005 — step state passed through jit without donation.

    A train step that takes the full TrainState but doesn't donate it
    doubles peak HBM (old + new state live across the step) — the
    difference between fitting the 113-TFLOPs config and OOMing at
    compile. Flagged only when the wrapped function resolvably takes a
    parameter named like the step state.
    """

    code = "TPU005"
    name = "missing-donation"
    severity = Severity.WARNING
    summary = "jit over step state without donate_argnums"

    _STATEY = {"state", "train_state", "opt_state", "carry_state"}

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        scope = module.scope
        for node in module.all_calls:
            if not scope.is_jit_call(node):
                continue
            if any(kw.arg in ("donate_argnums", "donate_argnames")
                   for kw in node.keywords):
                continue
            if not node.args:
                continue
            target = scope.resolve_local_def(node.args[0])
            if target is None:
                continue
            args = getattr(target, "args", None)
            if args is None:
                continue
            statey = [a.arg for a in args.args if a.arg in self._STATEY]
            if statey:
                yield self.finding(
                    module, node,
                    f"jit over '{getattr(target, 'name', '<lambda>')}' "
                    f"takes step state ({', '.join(statey)}) without "
                    "donate_argnums: old and new state coexist, doubling "
                    "peak HBM")


@register
class TracerBranchRule(Rule):
    """TPU006 — Python control flow on tracer values.

    ``if``/``while`` on a traced array concretizes it: TracerBoolConversion
    at best, a silently trace-time-frozen branch at worst. Branching on
    static python config is ubiquitous and fine, so the check demands
    dataflow evidence that the condition is an array: either the test
    itself calls into jnp/lax, or it references a local that was assigned
    from a jnp/jax call in the same function. ``x is None`` /
    ``isinstance`` guards are structural and exempt.
    """

    code = "TPU006"
    name = "tracer-branch"
    severity = Severity.ERROR
    summary = "Python branch on a traced value"

    # jax calls whose results are static python values, not tracers
    _STATIC_RESULTS = {"jax.numpy.ndim", "jax.numpy.shape", "jax.numpy.size",
                       "jax.eval_shape", "jax.devices", "jax.device_count",
                       "jax.local_device_count", "jax.default_backend",
                       "jax.tree.structure", "jax.tree_util.tree_structure"}

    def _is_array_call(self, module: ModuleInfo, call: ast.Call) -> bool:
        q = _qual(module, call.func)
        return bool(q) and q.startswith(("jax.numpy.", "jax.lax.",
                                         "jax.random.", "jax.nn.")) \
            and q not in self._STATIC_RESULTS

    def _arrayish_locals(self, module: ModuleInfo, fn) -> Set[str]:
        names: Set[str] = set()
        for node in module.fn_nodes(fn):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call) and \
                    self._is_array_call(module, node.value):
                for t in node.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            names.add(leaf.id)
        return names

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for fn in _walk_functions(module, traced_only=True):
            if isinstance(fn, ast.Lambda):
                continue
            arrayish = self._arrayish_locals(module, fn)
            for node in module.fn_nodes(fn):
                if isinstance(node, (ast.If, ast.While, ast.IfExp,
                                     ast.Assert)):
                    test = node.test
                else:
                    continue
                bad = self._tracer_evidence(module, test, arrayish)
                if bad:
                    kind = type(node).__name__.lower()
                    yield self.finding(
                        module, node,
                        f"python {kind} on traced value {bad} concretizes "
                        "it at trace time; use lax.cond / jnp.where")

    def _tracer_evidence(self, module: ModuleInfo, test: ast.AST,
                         arrayish: Set[str]) -> Optional[str]:
        # `a is None or <tracer>` still concretizes the tracer — the
        # structural-guard exemption applies per boolean operand, not to
        # the whole condition
        if isinstance(test, ast.BoolOp):
            for operand in test.values:
                bad = self._tracer_evidence(module, operand, arrayish)
                if bad:
                    return bad
            return None
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._tracer_evidence(module, test.operand, arrayish)
        for n in ast.walk(test):
            # structural guards are fine
            if isinstance(n, ast.Compare) and any(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                return None
            if isinstance(n, ast.Call):
                cf = n.func
                if isinstance(cf, ast.Name) and cf.id in (
                        "isinstance", "hasattr", "len", "callable"):
                    return None
        for n in ast.walk(test):
            if isinstance(n, ast.Call) and self._is_array_call(module, n):
                return f"'{ast.unparse(n)}'"
            if isinstance(n, ast.Name) and n.id in arrayish:
                return f"'{n.id}'"
        return None


@register
class ShardingSpecDriftRule(Rule):
    """TPU008 — sharding-constraint drift: non-canonical PartitionSpecs.

    The compiler's canonical output form for a spec drops trailing
    ``None`` entries, unwraps single-name tuples and never names size-1
    axes. A ``with_sharding_constraint`` / ``NamedSharding`` built from a
    non-canonical literal denotes the SAME placement but is a DIFFERENT
    jit cache key than what XLA emits for the step's outputs — the
    mismatch costs a spurious retrace of the whole program (caught live in
    PR 1: size-1-axis specs retraced the train step on step 2; the
    canonicalize_spec fix in runtime/zero/stages.py is the idiom). The
    statically detectable drift: trailing ``None`` entries, single-name
    tuple entries, and empty-tuple entries in P(...) literals passed to a
    constraint site.
    """

    code = "TPU008"
    name = "sharding-spec-drift"
    severity = Severity.WARNING
    summary = "non-canonical PartitionSpec at a sharding-constraint site"

    _SITES = {"jax.lax.with_sharding_constraint",
              "jax.sharding.NamedSharding",
              "jax.experimental.pjit.with_sharding_constraint"}
    _SPECS = {"jax.sharding.PartitionSpec",
              "jax.interpreters.pxla.PartitionSpec"}

    def _drift(self, module: ModuleInfo, spec: ast.Call) -> Optional[str]:
        args = spec.args
        if args and isinstance(args[-1], ast.Constant) \
                and args[-1].value is None:
            return "trailing None entries (canonical form strips them)"
        for a in args:
            if isinstance(a, ast.Tuple) and len(a.elts) == 1:
                return (f"single-name tuple entry {ast.unparse(a)} "
                        "(canonical form unwraps it)")
            if isinstance(a, ast.Tuple) and not a.elts:
                return "empty-tuple entry (canonical form is None)"
        return None

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        project = getattr(module, "project", None)
        seen_consts = set()
        for node in module.all_calls:
            if _qual(module, node.func) not in self._SITES:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        _qual(module, sub.func) in self._SPECS:
                    why = self._drift(module, sub)
                    if why:
                        yield self.finding(
                            module, sub,
                            f"non-canonical PartitionSpec at a constraint "
                            f"site: {why}; the spec names the same sharding "
                            "as its canonical form but is a different jit "
                            "cache key — a spurious retrace. Canonicalize "
                            "(drop trailing Nones / unwrap 1-tuples) or pass "
                            "through canonicalize_spec")
                    continue
                # module-level constant depth: a Name/Attribute argument
                # that resolves (through imports/re-exports, the TPU012
                # constant machinery) to a module-level ``SPEC = P(...)``
                # is checked against the SAME drift classes
                if project is None or not isinstance(
                        sub, (ast.Name, ast.Attribute)):
                    continue
                hit = project.resolve_spec_constant(module, sub)
                if hit is None:
                    continue
                def_module, spec_call = hit
                why = self._drift(def_module, spec_call)
                if not why:
                    continue
                if def_module is module:
                    # anchor at the definition: one finding per constant
                    # (however many sites read it), and --fix rewrites
                    # the P(...) literal once
                    if id(spec_call) in seen_consts:
                        continue
                    seen_consts.add(id(spec_call))
                    yield self.finding(
                        def_module, spec_call,
                        f"non-canonical PartitionSpec constant "
                        f"'{ast.unparse(sub)}' used at a constraint "
                        f"site: {why}; canonicalize the definition")
                else:
                    # cross-module: anchor at the USE (suppressions and
                    # subset lints stay per-file); not autofixable
                    yield self.finding(
                        module, sub,
                        f"constant '{ast.unparse(sub)}' "
                        f"({def_module.rel_path}:{spec_call.lineno}) is a "
                        f"non-canonical PartitionSpec: {why}; "
                        "canonicalize the definition")


@register
class ScanCarryWideningRule(Rule):
    """TPU009 — scan-carry dtype widening.

    ``lax.scan`` requires the carry entering and leaving the body to have
    the SAME pytree-of-dtypes: a body that returns a 16-bit carry widened
    to f32 (an ``astype(float32)``, a ``jnp.float32(...)`` wrap, an
    asarray-with-f32) either errors at trace time or silently runs the
    whole scan in f32, doubling the carry's HBM and bandwidth — grads and
    activations carried across layers are exactly the big tensors.
    Flagged only when the scan's ``init`` argument shows explicit 16-bit
    evidence, so intentional f32 scans never fire; a body that casts the
    carry back to 16-bit before returning is clean. ``lax.scan`` call
    sites only: ``nn.scan`` wraps a Module class and takes no init
    argument, so its carry dtypes are not statically visible here.
    """

    code = "TPU009"
    name = "scan-carry-widening"
    severity = Severity.WARNING
    summary = "16-bit scan carry returned widened to f32"

    _SCANS = {"jax.lax.scan"}

    def _halfish(self, module: ModuleInfo, node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, (ast.Attribute, ast.Name)) and \
                    _qual(module, n) in _HALF_NAMES:
                return True
            if isinstance(n, ast.Constant) and n.value in ("bfloat16",
                                                           "float16"):
                return True
        return False

    def _widening_cast(self, module: ModuleInfo,
                       expr: ast.AST) -> Optional[ast.AST]:
        """A node inside ``expr`` that casts to f32."""
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            if isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "astype" and n.args:
                a = n.args[0]
                if _qual(module, a) in _F32_NAMES or (
                        isinstance(a, ast.Constant)
                        and a.value == "float32"):
                    return n
            q = _qual(module, n.func)
            if q in _F32_NAMES and n.args:
                return n
            if q in ("jax.numpy.asarray", "jax.numpy.array"):
                dt = [kw.value for kw in n.keywords if kw.arg == "dtype"]
                dt += list(n.args[1:2])
                for d in dt:
                    if _qual(module, d) in _F32_NAMES or (
                            isinstance(d, ast.Constant)
                            and d.value == "float32"):
                        return n
        return None

    def _narrows_back(self, module: ModuleInfo, expr: ast.AST) -> bool:
        """The carry is re-cast to 16-bit somewhere in this expression —
        the widening was an intentional f32 island (accumulate in f32,
        carry in bf16), which is the correct idiom."""
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            if isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "astype" and n.args and \
                    self._halfish(module, n.args[0]):
                return True
            if _qual(module, n.func) in _HALF_NAMES:
                return True
        return False

    def _carry_exprs(self, module: ModuleInfo, body_fn):
        """Expressions the body returns as its carry (first element of a
        returned tuple), with one level of local-name dataflow."""
        assigns = {}        # name -> [value exprs assigned to it]
        for node in module.fn_nodes(body_fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            assigns.setdefault(leaf.id, []).append(node.value)
        out = []
        for node in module.fn_nodes(body_fn):
            if not (isinstance(node, ast.Return) and node.value is not None):
                continue
            v = node.value
            carry = v.elts[0] if isinstance(v, ast.Tuple) and v.elts else v
            if isinstance(carry, ast.Name):
                vals = assigns.get(carry.id, [])
                # any rebinding that narrows back to 16-bit clears the
                # name: the f32 hop was an intentional island
                if any(self._narrows_back(module, a) for a in vals):
                    continue
                out.extend(vals)
            out.append(carry)
        return out

    def _init_halfish(self, module: ModuleInfo, call: ast.Call,
                      init: ast.AST) -> bool:
        """16-bit evidence on the init expression — following a plain name
        to its assignments in the function enclosing the scan call."""
        if self._halfish(module, init):
            return True
        if not isinstance(init, ast.Name):
            return False
        encl = module.enclosing_function(call)
        if encl is None:
            return False
        for node in module.fn_nodes(encl):
            if isinstance(node, ast.Assign) and any(
                    isinstance(leaf, ast.Name) and leaf.id == init.id
                    for t in node.targets for leaf in ast.walk(t)):
                if self._halfish(module, node.value):
                    return True
        return False

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        scope = module.scope
        for node in module.all_calls:
            if _qual(module, node.func) not in self._SCANS:
                continue
            init = (node.args[1] if len(node.args) >= 2 else
                    next((kw.value for kw in node.keywords
                          if kw.arg == "init"), None))
            if init is None or not self._init_halfish(module, node, init):
                continue
            if not node.args:
                continue
            body = scope.resolve_local_def(node.args[0]) \
                if isinstance(node.args[0], ast.Name) else node.args[0]
            if not isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            for carry in self._carry_exprs(module, body):
                wide = self._widening_cast(module, carry)
                if wide is None or self._narrows_back(module, carry):
                    continue
                yield self.finding(
                    module, wide,
                    "scan carry initialized 16-bit but returned widened to "
                    f"f32 ('{ast.unparse(wide)}'): the carry dtype must be "
                    "invariant across iterations — this errors at trace "
                    "time, or silently runs the whole scan in f32 "
                    "(doubling carry HBM/bandwidth). Cast the carry back "
                    "to its input dtype before returning")
                break       # one finding per scan site


@register
class NamedScopeRule(Rule):
    """TPU010 — Pallas kernel launch without a jax.named_scope.

    A ``pl.pallas_call`` not wrapped in ``jax.named_scope`` shows up in
    profiler traces as an anonymous custom-call: the hottest hand-written
    regions in the program become unsearchable exactly where attribution
    matters most. The scope must be LEXICALLY visible at the launch site
    (a ``with jax.named_scope(...)`` in the same function, or the enclosing
    function decorated with it) — a caller's scope doesn't survive
    refactors that re-export the launcher.
    """

    code = "TPU010"
    name = "missing-named-scope"
    severity = Severity.WARNING
    summary = "pallas_call outside any jax.named_scope"

    _SCOPES = {"jax.named_scope", "jax.profiler.TraceAnnotation",
               "jax.profiler.StepTraceAnnotation"}

    def _scoped(self, module: ModuleInfo, node: ast.AST) -> bool:
        cur = module.parent(node)
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    ctx = item.context_expr
                    target = ctx.func if isinstance(ctx, ast.Call) else ctx
                    if _qual(module, target) in self._SCOPES:
                        return True
            elif isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in cur.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if _qual(module, target) == "jax.named_scope":
                        return True
                return False        # scope must be lexical within the launcher
            cur = module.parent(cur)
        return False

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in module.all_calls:
            if _qual(module, node.func) != \
                    "jax.experimental.pallas.pallas_call":
                continue
            if self._scoped(module, node):
                continue
            yield self.finding(
                module, node,
                "pl.pallas_call without jax.named_scope: the kernel is "
                "anonymous in profiler traces; wrap the launch in "
                "jax.named_scope('<kernel-name>')")


@register
class DevicePutInStepRule(Rule):
    """TPU014 — explicit device placement / host round-trip in a traced
    or hot step path.

    ``jax.device_put`` inside traced code is at best a placement hint
    the compiler already owns (shardings / out_shardings say it
    better) and at worst a mid-program cross-device copy XLA cannot
    schedule around — and in pipeline code it is exactly the
    inter-stage boundary crossing that belongs to the MPMD transfer
    channel (``runtime/pipe/mpmd/channel``), where it is explicit,
    fault-injectable (``pipe.xfer``), and supervised. On the HOST step
    path, a ``device_put`` whose argument is itself a host pull
    (``np.asarray(...)`` / ``jax.device_get(...)``) is a full
    device→host→device round-trip per step — the transfer the channel
    (or donation) exists to eliminate. Host-side placement outside the
    step path (init, checkpoint restore, offload staging, the channel
    itself) is the sanctioned idiom and is not flagged.
    """

    code = "TPU014"
    name = "device-put-in-step"
    severity = Severity.ERROR
    summary = "device_put/host round-trip in a jitted step path"

    _PULLS = {"numpy.asarray", "numpy.array", "jax.device_get"}

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        scope = module.scope
        for node in module.all_calls:
            if _qual(module, node.func) != "jax.device_put":
                continue
            traced = scope.in_traced(node)
            hot = scope.in_hot(node)
            if traced:
                yield self.finding(
                    module, node,
                    "jax.device_put inside traced code: placement belongs "
                    "to the compiler (shardings/out_shardings); an "
                    "inter-stage crossing belongs to the MPMD transfer "
                    "channel (runtime/pipe/mpmd/channel)")
                continue
            if hot and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Call) and \
                        _qual(module, arg.func) in self._PULLS:
                    yield self.finding(
                        module, node,
                        "device->host->device round-trip on the step path "
                        "(device_put of a host pull): route the transfer "
                        "through the MPMD channel or keep the value on "
                        "device", severity=Severity.WARNING)


@register
class UnboundedBlockingRule(Rule):
    """TPU015 — unbounded blocking call in a supervision module.

    Supervisors, watchdogs, fleets and elastic agents exist to convert
    hangs into diagnosable exits — so THEIR OWN code must never block
    without a deadline. A ``lock.acquire()`` with no timeout, a
    ``queue.get()`` that can wait forever, an ``Event.wait()`` with no
    bound or a ``thread.join()`` without ``timeout=`` turns the
    component that detects wedges into one: the PR-6 review passes fixed
    exactly this class by hand three times (the heartbeat writer's exit
    paths, the watchdog's terminal stamp, the preemption handler's
    self-deadlocking re-acquire). The rule fires only in the supervision
    modules (``supervisor.py`` / ``watchdog.py`` / ``fleet.py`` /
    ``elastic_agent.py`` / ``straggler.py`` / the MPMD ``driver.py`` /
    the round-18 transfer fabric ``endpoint.py``/``sockets.py``/
    ``local.py`` and process fleet ``procfleet.py``/
    ``replica_worker.py``) — ordinary code is allowed to wait.

    Receiver-name vocabulary keeps the check precise: ``.acquire()`` on
    lock-ish names, ``.wait()`` on event/condition-ish names (a
    ``proc.wait()`` on a Popen is the monitor thread's whole job and is
    NOT flagged), ``.get()`` on queue-ish names, and ANY zero-argument
    ``.join()`` (string/path joins always carry an argument; a bare
    thread join is exactly the target). Calls carrying a ``timeout``
    (kwarg, or a positional in the method's timeout SLOT) or
    ``blocking=False`` are bounded and clean — but ``acquire(True)``,
    ``get(1)`` and ``wait(None)`` are explicit spellings of "block
    forever" and stay flagged.
    """

    code = "TPU015"
    name = "unbounded-blocking"
    severity = Severity.WARNING
    summary = "unbounded blocking call in a supervision module"

    #: files whose job is supervision — the only place the rule fires.
    #: Round 18 adds the transfer-fabric layer (runtime/fabric/) and the
    #: process-placement fleet: a channel or hub that blocks forever IS
    #: the wedge the supervision stack exists to catch.
    _MODULES = ("supervisor.py", "watchdog.py", "fleet.py",
                "elastic_agent.py", "straggler.py", "driver.py",
                "endpoint.py", "sockets.py", "local.py",
                "procfleet.py", "replica_worker.py", "autoscale.py")
    _LOCKISH = re.compile(r"lock|mutex|sem", re.I)
    _EVENTISH = re.compile(r"evt|event|done|stop|ready|cond|barrier|sig",
                           re.I)
    _QUEUEISH = re.compile(r"queue|fifo|inbox|mailbox|chan|^q$|_q$", re.I)

    @staticmethod
    def _receiver(func: ast.Attribute) -> str:
        v = func.value
        if isinstance(v, ast.Name):
            return v.id
        if isinstance(v, ast.Attribute):
            return v.attr
        return ""

    @staticmethod
    def _bounded(node: ast.Call) -> bool:
        """A timeout (kwarg, or a positional in the TIMEOUT slot) or
        blocking=False makes the call bounded/non-blocking. Positional
        slots are method-shaped: ``acquire``/``get`` take
        ``(blocking, timeout)`` — a lone positional is just an explicit
        blocking flag, so ``acquire(True)`` / ``get(1)`` stay flagged —
        while ``wait`` takes ``(timeout)``, where an explicit ``None``
        (``wait(None)``) spells unbounded."""
        for kw in node.keywords:
            if kw.arg == "timeout":
                return True
            if kw.arg in ("blocking", "block") and isinstance(
                    kw.value, ast.Constant) and kw.value.value is False:
                return True
        for arg in node.args:
            if isinstance(arg, ast.Constant) and arg.value is False:
                return True           # acquire(False) / get(False)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("acquire", "get"):
            return len(node.args) >= 2    # acquire(True, 5) / get(1, 2)
        if not node.args:
            return False
        first = node.args[0]
        return not (isinstance(first, ast.Constant)
                    and first.value is None)  # wait(None) blocks forever

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        base = module.rel_path.rsplit("/", 1)[-1]
        if base not in self._MODULES:
            return
        for node in module.all_calls:
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            recv = self._receiver(f)
            if f.attr == "join":
                if node.args or node.keywords:
                    continue          # bounded join, or a str/path join
                yield self.finding(
                    module, node,
                    f"{recv or 'thread'}.join() without timeout= in a "
                    "supervision module: a wedged thread blocks the "
                    "supervisor that exists to catch wedges — bound it "
                    "and handle the still-alive case")
                continue
            if self._bounded(node):
                continue
            if f.attr == "acquire" and self._LOCKISH.search(recv):
                yield self.finding(
                    module, node,
                    f"{recv}.acquire() without timeout= in a supervision "
                    "module: a holder wedged in I/O (or the same thread "
                    "re-entering from a signal handler) deadlocks the "
                    "exit path — acquire(timeout=...) and degrade")
            elif f.attr == "wait" and self._EVENTISH.search(recv):
                yield self.finding(
                    module, node,
                    f"{recv}.wait() without a timeout in a supervision "
                    "module: an event that never fires parks this thread "
                    "forever — wait(timeout) in a loop keeps the "
                    "monitor's own liveness")
            elif f.attr == "get" and self._QUEUEISH.search(recv):
                yield self.finding(
                    module, node,
                    f"{recv}.get() without timeout= in a supervision "
                    "module: an empty queue blocks forever — "
                    "get(timeout=...) and re-check the stop flag")


@register
class PRNGReuseRule(Rule):
    """TPU007 — PRNG key reuse.

    Passing one key to two sampling calls correlates the streams (same
    bits), and sampling with a loop-invariant key repeats the draw every
    iteration — both are silent statistical bugs, not crashes. Keys are
    consumed once; thread new ones with split/fold_in.
    """

    code = "TPU007"
    name = "prng-reuse"
    severity = Severity.ERROR
    summary = "PRNG key consumed more than once"

    _NONCONSUMING = {"split", "fold_in", "PRNGKey", "key", "clone",
                     "key_data", "wrap_key_data", "key_impl"}
    _KEYISH = re.compile(r"rng|key|prng", re.I)

    def _consuming_key_arg(self, module: ModuleInfo,
                           call: ast.Call) -> Optional[str]:
        q = _qual(module, call.func)
        if not q or not q.startswith("jax.random."):
            return None
        if q.rsplit(".", 1)[1] in self._NONCONSUMING:
            return None
        for arg in call.args[:1]:
            if isinstance(arg, ast.Name) and self._KEYISH.search(arg.id):
                return arg.id
        return None

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for fn in module.scope._defs:
            if isinstance(fn, ast.Lambda):
                continue
            yield from self._check_body(module, fn)

    @staticmethod
    def _branch_path(module: ModuleInfo, node: ast.AST):
        """(if-node, arm) pairs on the ancestor chain — used to recognize
        mutually exclusive if/else arms."""
        arms = []
        child, cur = node, module.parent(node)
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            if isinstance(cur, ast.If):
                if any(child is s for s in cur.body):
                    arms.append((id(cur), "body"))
                elif any(child is s for s in cur.orelse):
                    arms.append((id(cur), "orelse"))
            child, cur = cur, module.parent(cur)
        return arms

    @classmethod
    def _exclusive(cls, module: ModuleInfo, a: ast.AST, b: ast.AST) -> bool:
        pa = dict(cls._branch_path(module, a))
        return any(pa.get(if_id) not in (None, arm)
                   for if_id, arm in cls._branch_path(module, b))

    def _check_body(self, module: ModuleInfo, fn) -> Iterator[Finding]:
        flagged = set()         # nodes already reported (sequential + loop
                                # checks can overlap on the same call)
        consumed = {}           # key name -> first consuming node
        events = []             # (lineno, kind, name, node) in source order
        for node in module.fn_nodes(fn):
            if isinstance(node, ast.Call):
                k = self._consuming_key_arg(module, node)
                if k:
                    events.append((node.lineno, "use", k, node))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            events.append(
                                (node.lineno, "bind", leaf.id, node))
        for lineno, kind, name, node in sorted(
                events, key=lambda e: (e[0],
                                       0 if e[1] == "bind" else 1)):
            if kind == "bind":
                consumed.pop(name, None)
            elif name in consumed and not self._exclusive(
                    module, consumed[name], node):
                flagged.add(node)
                yield self.finding(
                    module, node,
                    f"PRNG key '{name}' already consumed at line "
                    f"{consumed[name].lineno}; split/fold_in a fresh key "
                    "(reuse correlates the random streams)")
            elif name not in consumed:
                consumed[name] = node
        # loop-invariant key: consumed inside a loop body with no rebinding
        # of the key anywhere in that loop
        for node in module.fn_nodes(fn):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            bound_in_loop = set()
            for n in ast.walk(node):
                if isinstance(n, (ast.Assign, ast.AugAssign)):
                    ts = n.targets if isinstance(n, ast.Assign) \
                        else [n.target]
                    for t in ts:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name):
                                bound_in_loop.add(leaf.id)
            if isinstance(node, ast.For):
                for leaf in ast.walk(node.target):
                    if isinstance(leaf, ast.Name):
                        bound_in_loop.add(leaf.id)
            for n in ast.walk(node):
                if isinstance(n, ast.Call) and n not in flagged:
                    k = self._consuming_key_arg(module, n)
                    if k and k not in bound_in_loop:
                        flagged.add(n)
                        yield self.finding(
                            module, n,
                            f"PRNG key '{k}' is loop-invariant: every "
                            "iteration draws the same bits; fold_in the "
                            "loop index")
