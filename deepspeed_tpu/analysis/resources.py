"""Resource-lifecycle model: who acquires, who must release, on every path.

Rides the project-wide :class:`callgraph.ProjectIndex` the way locks.py
does for the lock/thread model. A catalog maps *acquire sites* to their
*release obligations*:

=============  =======================================  ====================
kind           acquired by                              released by
=============  =======================================  ====================
blocks         ``pool.alloc(n)`` / ``pool.fork(b)`` /   ``pool.release(b)``
               ``prefix_cache.match(p)`` (2nd elt)
socket         ``socket.socket`` / ``create_connection``  ``.close()`` /
               / ``.accept()`` (1st elt) / fabric          ``.shutdown()`` /
               ``SocketEndpoint``/``LocalEndpoint``/       ``with``
               ``HubConn`` construction
popen          ``subprocess.Popen(...)``                ``.wait/kill/terminate
                                                        /communicate``
thread         ``threading.Thread(target=...)`` +       ``.join()`` (or
               ``.start()`` (TPU023 only)               ``daemon=True``)
file           ``open`` / ``os.fdopen`` / ``tempfile.*``  ``.close()`` /
                                                          ``.cleanup()``
heartbeat      ``HeartbeatWriter(...)``                 ``.close()`` /
                                                        ``.stamp_terminal()``
staging        ``os.makedirs(<tag>.tmp)``               publish (``os.replace
                                                        /rename``) or
                                                        quarantine/``rmtree``
=============  =======================================  ====================

Ownership-transfer exemptions are resolved interprocedurally: a resource
stored on ``self``/a container, returned or yielded to the caller, or
handed to a callee that provably discharges its parameter (releases it,
stores it, re-returns it, or passes it on) is no longer this function's
obligation.  Calls the index cannot resolve are assumed to take
ownership — the model prefers a missed leak over a false alarm.

Blind spots (documented in docs/LINT.md): aliasing through containers
(``pools[i].alloc`` results collected into dicts), dynamically computed
attribute names, and cross-process handles (an fd inherited by a
``Popen`` child is invisible here).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .callgraph import FunctionNode, ProjectIndex

# ------------------------------------------------------------------ catalog

#: per-kind release verbs: a call ``N.<verb>()`` (or ``owner.<verb>(N)``
#: for arg-style kinds) discharges the obligation
RELEASE_VERBS: Dict[str, Set[str]] = {
    "blocks": {"release"},
    "socket": {"close", "shutdown", "detach"},
    "popen": {"wait", "kill", "terminate", "communicate"},
    "thread": {"join"},
    "file": {"close", "cleanup"},
    "heartbeat": {"close", "stamp_terminal"},
    "staging": {"replace", "rename", "rmtree"},
}

#: attribute reads/calls that are legitimate AFTER release (TPU025)
POST_RELEASE_OK: Dict[str, Set[str]] = {
    "blocks": set(),
    "socket": {"close", "fileno", "detach", "shutdown"},
    "popen": {"poll", "wait", "kill", "terminate", "communicate",
              "send_signal", "returncode", "pid", "stdout", "stderr",
              "stdin", "args"},
    "thread": {"join", "is_alive", "name", "daemon", "ident",
               "native_id"},
    "file": {"close", "closed", "name", "mode"},
    "heartbeat": {"close", "stamp_terminal", "path"},
    "staging": set(),
}

#: function-name fragments that count as staging publish/quarantine
_STAGING_DISCHARGE_FRAGMENTS = ("quarantine", "publish", "promote")

_SOCKET_CTORS = {"socket.socket", "socket.create_connection"}
_ENDPOINT_CTOR_SUFFIXES = ("SocketEndpoint", "LocalEndpoint", "HubConn")
_FILE_CTORS = {"open", "os.fdopen", "tempfile.TemporaryFile",
               "tempfile.NamedTemporaryFile", "tempfile.TemporaryDirectory",
               "tempfile.mkdtemp"}
_POOL_ACQUIRE_ATTRS = {"alloc", "fork"}

_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_LOOP = (ast.For, ast.AsyncFor, ast.While)


def _walk_no_fn(node: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does not descend into nested function bodies —
    code inside a closure/handler def runs on a different path than the
    statement that defines it."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, _FN):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


class Acquire:
    """One catalogued acquire site inside one function."""

    __slots__ = ("kind", "call", "stmt", "name", "fn", "module", "how")

    def __init__(self, kind: str, call: ast.Call, stmt: ast.stmt,
                 name: Optional[str], fn: Optional[ast.AST], module,
                 how: str):
        self.kind = kind
        self.call = call
        self.stmt = stmt
        self.name = name      # simple binding name, or None
        self.fn = fn
        self.module = module
        self.how = how        # human description of the acquire


class _Protect:
    pass


_PROTECT = _Protect()


class _Break(Exception):
    """Control-flow signal inside the forward scan: a ``break`` routes
    the scan past the enclosing loop."""


class ResourceModel:
    """Project-wide resource analysis; build once per lint run via
    :func:`get_resource_model` (cached on the index, LockModel-style)."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self._fail_memo: Dict[ast.AST, bool] = {}
        self._discharge_memo: Dict[Tuple[int, str], bool] = {}

    # ----------------------------------------------------- acquire discovery

    def acquires_in(self, module) -> List[Acquire]:
        out: List[Acquire] = []
        for call in module.all_calls:
            kind, how, tuple_idx = self._acquire_kind(module, call)
            if kind is None:
                continue
            stmt = self._stmt_of(call)
            if stmt is None:
                continue
            name = self._binding_name(module, call, stmt, kind, tuple_idx)
            out.append(Acquire(kind, call, stmt, name,
                               module.enclosing_function(call), module, how))
        return out

    def _acquire_kind(self, module, call: ast.Call
                      ) -> Tuple[Optional[str], str, int]:
        """(kind, description, tuple-unpack index) or (None, "", 0)."""
        f = call.func
        q = module.scope.imports.qualify(f) or ""
        if q in _SOCKET_CTORS:
            return "socket", q, 0
        if q.split(".")[-1] in ("Popen",) and (
                q in ("Popen", "subprocess.Popen")
                or q.endswith(".subprocess.Popen")):
            return "popen", "subprocess.Popen", 0
        if q in ("Thread", "threading.Thread"):
            return "thread", "threading.Thread", 0
        if q in _FILE_CTORS:
            return "file", q, 0
        if q.split(".")[-1] == "HeartbeatWriter":
            return "heartbeat", "HeartbeatWriter", 0
        if any(q.split(".")[-1] == s for s in _ENDPOINT_CTOR_SUFFIXES):
            return "socket", q.split(".")[-1], 0
        if q in ("os.makedirs", "os.mkdir") and self._is_staging_arg(
                module, call):
            return "staging", "staging dir (<tag>.tmp)", 0
        if isinstance(f, ast.Attribute):
            if f.attr == "accept" and q != "os.accept":
                return "socket", ".accept()", 0
            if f.attr in _POOL_ACQUIRE_ATTRS and q not in ("os.fork",):
                recv = self._expr_text(module, f.value)
                if "pool" in recv or recv in ("self", ""):
                    return "blocks", f".{f.attr}() on {recv or 'pool'}", 0
            if f.attr == "match" and "prefix_cache" in self._expr_text(
                    module, f.value):
                return "blocks", "prefix_cache.match (forked refs)", 1
        return None, "", 0

    def _is_staging_arg(self, module, call: ast.Call) -> bool:
        if not call.args:
            return False
        arg = call.args[0]
        text = self._expr_text(module, arg)
        if "STAGING_SUFFIX" in text or ".tmp" in text:
            return True
        if isinstance(arg, ast.Name):
            fn = module.enclosing_function(call)
            for n in module.nodes_by_fn.get(fn, ()):
                if isinstance(n, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == arg.id
                        for t in n.targets):
                    rhs = self._expr_text(module, n.value)
                    if "STAGING_SUFFIX" in rhs or ".tmp" in rhs:
                        return True
        return False

    @staticmethod
    def _expr_text(module, node: ast.AST) -> str:
        try:
            return ast.get_source_segment(module.source, node) or ""
        except Exception:
            return ""

    # ------------------------------------------------------ binding & stmts

    @staticmethod
    def _stmt_of(node: ast.AST) -> Optional[ast.stmt]:
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = getattr(cur, "_gl_parent", None)
        return cur

    def _binding_name(self, module, call: ast.Call, stmt: ast.stmt,
                      kind: str, tuple_idx: int) -> Optional[str]:
        """The simple local name the resource lands in, or None (the
        analysis then decides between 'discarded' and 'consumed')."""
        if kind == "staging":
            arg = call.args[0] if call.args else None
            return arg.id if isinstance(arg, ast.Name) else None
        if isinstance(stmt, ast.Assign) and stmt.value is call \
                and len(stmt.targets) == 1:
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                return t.id
            if isinstance(t, ast.Tuple) and tuple_idx < len(t.elts):
                elt = t.elts[tuple_idx]
                return elt.id if isinstance(elt, ast.Name) else None
        return None

    # ------------------------------------------------------- leak (TPU022)

    def check_leak(self, acq: Acquire
                   ) -> Optional[Tuple[ast.AST, str]]:
        """None when every path discharges the acquire; else
        ``(witness_node, why)`` — the first raise-capable site (or the
        acquire itself) past which the resource is stranded."""
        module, call, stmt = acq.module, acq.call, acq.stmt
        if acq.kind == "thread":
            return None                      # TPU023's domain
        # acquired directly into a with-item: the runtime releases it
        wi = self._enclosing_withitem(call)
        if wi is not None:
            return None
        if acq.kind != "staging":
            shape = self._birth_shape(module, call, stmt)
            if shape == "transferred":
                return None
            if shape == "discarded":
                return (call, "the handle is discarded at the acquire "
                              "site — nothing can ever release it")
            if shape == "consumed":
                return None                  # flows into an expression the
                #                              caller owns (conservative)
        if acq.name is None:
            return None
        # releasing a constituent releases the wrapper: HubConn(sock)
        # is discharged when the handler closes `sock`
        names = {acq.name} | self._constituent_names(acq)
        # lexically inside a try whose handler/finally discharges it
        if self._guarded_by_enclosing_try(module, stmt, names, acq.kind):
            return None
        return self._scan_after(module, stmt, acq.name, names, acq.kind)

    @staticmethod
    def _constituent_names(acq: Acquire) -> Set[str]:
        if acq.kind == "staging":
            return set()
        out: Set[str] = set()
        for a in list(acq.call.args) + [kw.value for kw in
                                        acq.call.keywords]:
            if isinstance(a, ast.Name):
                out.add(a.id)
        return out

    def _enclosing_withitem(self, call: ast.Call) -> Optional[ast.withitem]:
        parent = getattr(call, "_gl_parent", None)
        return parent if isinstance(parent, ast.withitem) else None

    def _birth_shape(self, module, call: ast.Call, stmt: ast.stmt) -> str:
        """How the acquire's value leaves the acquiring expression:
        'bound' (simple name — scan forward), 'transferred' (stored on
        self/container, returned, yielded), 'discarded' (bare Expr, or a
        non-release method chained on the fresh handle), 'consumed'
        (nested in a larger expression — assumed owned there)."""
        parent = getattr(call, "_gl_parent", None)
        if isinstance(parent, ast.Assign) and parent.value is call:
            t = parent.targets[0] if len(parent.targets) == 1 else None
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                return "transferred"
            return "bound"
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom,
                               ast.Await)):
            return "transferred"
        if isinstance(parent, ast.Expr):
            return "discarded"
        if isinstance(parent, ast.Attribute):
            # method chained on the fresh handle: Popen(...).wait() is a
            # release; open(...).read() never closes
            gp = getattr(parent, "_gl_parent", None)
            if isinstance(gp, ast.Call) and gp.func is parent:
                kind, _, _ = self._acquire_kind(module, call)
                if parent.attr in RELEASE_VERBS.get(kind or "", set()) or \
                        (kind == "thread" and parent.attr == "start"):
                    return "consumed"
                return "discarded"
        return "consumed"

    def _guarded_by_enclosing_try(self, module, stmt: ast.stmt,
                                  names: Set[str], kind: str) -> bool:
        cur: Optional[ast.AST] = stmt
        while cur is not None and not isinstance(cur, _FN):
            parent = getattr(cur, "_gl_parent", None)
            if isinstance(parent, ast.Try) and cur in parent.body:
                cleanup: List[ast.AST] = list(parent.finalbody)
                cleanup.extend(parent.handlers)
                for region in cleanup:
                    if self._region_discharges(module, region, names, kind):
                        return True
            cur = parent
        return False

    def _region_discharges(self, module, region: ast.AST, names: Set[str],
                           kind: str) -> bool:
        for n in ast.walk(region):
            if self._node_discharges(module, n, names, kind):
                return True
        return False

    # ---- the forward scan -------------------------------------------------

    def _scan_after(self, module, stmt: ast.stmt, name: str,
                    names: Set[str], kind: str
                    ) -> Optional[Tuple[ast.AST, str]]:
        cur: ast.AST = stmt
        while True:
            owner = getattr(cur, "_gl_parent", None)
            seq = self._containing_block(owner, cur)
            if seq is not None:
                i = seq.index(cur)
                try:
                    r = self._scan_block(module, seq[i + 1:], name, names,
                                         kind)
                except _Break:
                    cur = self._climb_past_loop(cur)
                    continue
                if r is _PROTECT:
                    return None
                if r is not None:
                    return r
                # fell off a try body: the else-block runs next
                if isinstance(owner, ast.Try) and seq is owner.body:
                    try:
                        r = self._scan_block(module, owner.orelse, name,
                                             names, kind)
                    except _Break:
                        cur = self._climb_past_loop(owner)
                        continue
                    if r is _PROTECT:
                        return None
                    if r is not None:
                        return r
            if owner is None or isinstance(owner, (ast.Module,) + _FN):
                return (stmt, "no path from here releases or hands off "
                              "the resource before the function ends")
            if isinstance(owner, _LOOP) and seq is not None \
                    and not self._has_break(owner):
                return (stmt, "the loop iterates without releasing the "
                              "previous iteration's resource")
            if isinstance(owner, ast.excepthandler):
                owner = getattr(owner, "_gl_parent", None)
            cur = owner

    @staticmethod
    def _containing_block(owner: Optional[ast.AST], stmt: ast.AST
                          ) -> Optional[List[ast.stmt]]:
        if owner is None:
            return None
        for field in ("body", "orelse", "finalbody"):
            seq = getattr(owner, field, None)
            if isinstance(seq, list) and stmt in seq:
                return seq
        return None

    @staticmethod
    def _climb_past_loop(node: ast.AST) -> ast.AST:
        cur = node
        while cur is not None and not isinstance(cur, _LOOP):
            cur = getattr(cur, "_gl_parent", None)
        return cur

    @staticmethod
    def _has_break(loop: ast.AST) -> bool:
        for n in ast.walk(loop):
            if isinstance(n, ast.Break):
                return True
        return False

    def _scan_block(self, module, stmts: List[ast.stmt], name: str,
                    names: Set[str], kind: str):
        """Scan statements in execution order. Returns _PROTECT when the
        obligation is discharged, a ``(node, why)`` leak witness when a
        raise-capable site precedes any discharge, or None (keep
        scanning the enclosing block). Raises :class:`_Break` when an
        unconditional ``break`` routes control past the loop."""
        for s in stmts:
            r = self._classify(module, s, name, names, kind)
            if r is not None:
                return r
        return None

    def _classify(self, module, s: ast.stmt, name: str, names: Set[str],
                  kind: str):
        if isinstance(s, ast.Break):
            raise _Break()
        if isinstance(s, (ast.Continue,)):
            return (s, "the loop continues without releasing the resource")
        if isinstance(s, (ast.Return, ast.Yield)) or (
                isinstance(s, ast.Expr)
                and isinstance(s.value, (ast.Yield, ast.YieldFrom))):
            if self._mentions_any(s, names):
                return _PROTECT          # ownership handed to the caller
            return (s, "the function returns without releasing the "
                       "resource")
        if isinstance(s, ast.Raise):
            return (s, "raises with the resource still held")
        if isinstance(s, ast.Assert):
            return (s, "a failing assert strands the resource")
        if isinstance(s, ast.Try):
            for region in list(s.finalbody) + list(s.handlers):
                if self._region_discharges(module, region, names, kind):
                    return _PROTECT
            r = self._scan_block(module, s.body, name, names, kind)
            if r is not None:
                return r
            r = self._scan_block(module, s.orelse, name, names, kind)
            if r is not None:
                return r
            return self._scan_block(module, s.finalbody, name, names, kind)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                if self._mentions_any(item.context_expr, names):
                    return _PROTECT      # `with sock:` / `with closing(s)`
            return self._scan_block(module, s.body, name, names, kind)
        if isinstance(s, (ast.If,) + _LOOP):
            # optimistic on branches: a discharge anywhere inside counts
            if self._region_discharges(module, s, names, kind):
                return _PROTECT
            return self._risky_in(module, s, name, kind)
        # simple statement: discharge first, then raise-capability
        if self._region_discharges(module, s, names, kind):
            return _PROTECT
        if self._reassigns(s, name):
            return _PROTECT              # binding reset: tracking ends
        return self._risky_in(module, s, name, kind)

    # ---- event classification --------------------------------------------

    @staticmethod
    def _mentions(node: ast.AST, name: str) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id == name:
                return True
        return False

    @staticmethod
    def _mentions_any(node: ast.AST, names: Set[str]) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in names:
                return True
        return False

    @staticmethod
    def _reassigns(s: ast.stmt, name: str) -> bool:
        targets: List[ast.AST] = []
        if isinstance(s, ast.Assign):
            targets = s.targets
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            targets = [s.target]
        elif isinstance(s, ast.Delete):
            targets = s.targets
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name) and n.id == name:
                    return True
        return False

    def _node_discharges(self, module, n: ast.AST, names: Set[str],
                         kind: str) -> bool:
        """Does this single node discharge the obligation on any of
        ``names`` (the binding plus its constituent aliases)?"""
        if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)) \
                and n is not None and self._mentions_any(n, names):
            return True
        if isinstance(n, ast.Assign):
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in n.targets) and \
                    self._mentions_any(n.value, names):
                return True                  # stored on self / a container
            if any(isinstance(t, (ast.Name, ast.Tuple))
                   for t in n.targets) and \
                    self._mentions_any(n.value, names):
                return True                  # aliased: tracking moves on
        if isinstance(n, ast.Call):
            return self._call_discharges(module, n, names, kind)
        return False

    def _call_discharges(self, module, call: ast.Call, names: Set[str],
                         kind: str) -> bool:
        f = call.func
        # N.release_verb()
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id in names:
            return f.attr in RELEASE_VERBS[kind]
        if not self._arg_mentions_any(call, names):
            return False
        last = ""
        if isinstance(f, ast.Attribute):
            last = f.attr
        elif isinstance(f, ast.Name):
            last = f.id
        if kind == "staging":
            # path strings flow through join/open constantly; only the
            # publish/quarantine vocabulary discharges a staging dir
            return (last in RELEASE_VERBS["staging"]
                    or any(fr in last.lower()
                           for fr in _STAGING_DISCHARGE_FRAGMENTS))
        if last in RELEASE_VERBS[kind]:
            return True                      # pool.release(blocks) style
        # handed to a callee: ownership transfer — unless the callee is
        # resolvable and provably does NOT discharge its parameter
        fnode = self.index.resolve_call(module, call)
        if fnode is None:
            return True
        for name in names:
            if not self._arg_mentions(call, name):
                continue
            pname = self._param_for_arg(fnode, call, name)
            if pname is None:
                return True
            if self._param_discharged(fnode, pname, depth=3):
                return True
        return False

    @staticmethod
    def _arg_mentions(call: ast.Call, name: str) -> bool:
        for sub in list(call.args) + [kw.value for kw in call.keywords]:
            for n in ast.walk(sub):
                if isinstance(n, ast.Name) and n.id == name:
                    return True
        return False

    @staticmethod
    def _arg_mentions_any(call: ast.Call, names: Set[str]) -> bool:
        for sub in list(call.args) + [kw.value for kw in call.keywords]:
            for n in ast.walk(sub):
                if isinstance(n, ast.Name) and n.id in names:
                    return True
        return False

    @staticmethod
    def _param_for_arg(fnode: FunctionNode, call: ast.Call,
                       name: str) -> Optional[str]:
        """Callee parameter the argument ``name`` binds to (best effort;
        None = unknown, treated as a discharge)."""
        fn = fnode.fn
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        params = [a.arg for a in fn.args.args]
        offset = 1 if params and params[0] in ("self", "cls") else 0
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Name) and a.id == name:
                j = i + offset
                return params[j] if j < len(params) else None
        for kw in call.keywords:
            if isinstance(kw.value, ast.Name) and kw.value.id == name:
                return kw.arg
        return None                          # nested in a bigger expression

    def _param_discharged(self, fnode: FunctionNode, pname: str,
                          depth: int) -> bool:
        """Does the callee release / store / re-return / pass on its
        parameter? Memoized; unresolvable onward calls count as yes."""
        key = (id(fnode.fn), pname)
        if key in self._discharge_memo:
            return self._discharge_memo[key]
        self._discharge_memo[key] = True     # cycle guard: optimistic
        module, fn = fnode.module, fnode.fn
        result = False
        all_verbs = set().union(*RELEASE_VERBS.values())
        for n in module.fn_nodes(fn, subtree=True):
            if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
                if self._mentions(n, pname):
                    result = True
                    break
            elif isinstance(n, ast.Assign):
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in n.targets) and \
                        self._mentions(n.value, pname):
                    result = True
                    break
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                if any(self._mentions(item.context_expr, pname)
                       for item in n.items):
                    result = True
                    break
            elif isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and \
                        f.value.id == pname:
                    if f.attr in all_verbs:
                        result = True
                        break
                    continue
                if not self._arg_mentions(n, pname):
                    continue
                callee = self.index.resolve_call(module, n)
                if callee is None:
                    result = True            # handed onward, unresolvable
                    break
                if depth <= 0:
                    result = True
                    break
                nxt = self._param_for_arg(callee, n, pname)
                if nxt is None or self._param_discharged(
                        callee, nxt, depth - 1):
                    result = True
                    break
        self._discharge_memo[key] = result
        return result

    def _risky_in(self, module, node: ast.AST, name: str, kind: str
                  ) -> Optional[Tuple[ast.AST, str]]:
        """First raise-capable site in the subtree, as (node, why).
        Nested function bodies are pruned: a raise inside a closure
        fires on the closure's path, not this one."""
        for n in _walk_no_fn(node):
            if isinstance(n, ast.Raise):
                return (n, "raises with the resource still held")
            if isinstance(n, ast.Assert):
                return (n, "a failing assert strands the resource")
            if isinstance(n, ast.Call):
                why = self._call_risk(module, n, name, kind)
                if why is not None:
                    return (n, why)
        return None

    def _call_risk(self, module, call: ast.Call, name: str,
                   kind: str) -> Optional[str]:
        f = call.func
        q = module.scope.imports.qualify(f) or ""
        if self._is_failpoint(q):
            return "a keyed chaos failpoint fires here with the " \
                   "resource still held"
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == name and \
                f.attr not in RELEASE_VERBS[kind]:
            return (f"'{name}.{f.attr}()' can raise before the resource "
                    "is released or handed off")
        fnode = self.index.resolve_call(module, call)
        if fnode is not None and self._reaches_failpoint(fnode, depth=3):
            return (f"callee '{fnode.qualname}' reaches a chaos "
                    "failpoint with the resource still held")
        return None

    @staticmethod
    def _is_failpoint(q: str) -> bool:
        return q.endswith("chaos.failpoint") or q.endswith("chaos.flag")

    def _reaches_failpoint(self, fnode: FunctionNode, depth: int) -> bool:
        fn = fnode.fn
        if fn in self._fail_memo:
            return self._fail_memo[fn]
        self._fail_memo[fn] = False          # cycle guard
        module = fnode.module
        result = False
        for n in module.fn_nodes(fn, subtree=False):
            if not isinstance(n, ast.Call):
                continue
            q = module.scope.imports.qualify(n.func) or ""
            if self._is_failpoint(q):
                result = True
                break
            if depth > 0:
                callee = self.index.resolve_call(module, n)
                if callee is not None and self._reaches_failpoint(
                        callee, depth - 1):
                    result = True
                    break
        self._fail_memo[fn] = result
        return result

    # ---------------------------------------------------- threads (TPU023)

    def thread_leaks(self, module
                     ) -> Iterator[Tuple[ast.Call, str, Optional[str]]]:
        """Non-daemon ``Thread(target=...)`` that is started but joined
        nowhere: ``(ctor_call, description, owning_attr)``."""
        for call in module.all_calls:
            q = module.scope.imports.qualify(call.func) or ""
            if q not in ("Thread", "threading.Thread"):
                continue
            if self._kw_true(call, "daemon"):
                continue
            fn = module.enclosing_function(call)
            stmt = self._stmt_of(call)
            name = self._binding_name(module, call, stmt, "thread", 0) \
                if stmt is not None else None
            # chained `Thread(...).start()` with no binding
            parent = getattr(call, "_gl_parent", None)
            chained_start = (isinstance(parent, ast.Attribute)
                             and parent.attr == "start")
            if name is None and not chained_start:
                continue                     # consumed elsewhere: assume
                #                              the new owner joins it
            started, joined, daemon_later, attr = \
                self._thread_fate(module, fn, name) if name else \
                (True, False, False, None)
            if chained_start:
                started = True
            if not started or daemon_later or joined:
                continue
            if attr is not None and self._attr_joined(module, attr):
                continue
            if attr is None and name is not None and \
                    self._escapes(module, fn, name):
                continue                     # handed to a ledger/supervisor
            yield call, q, attr

    @staticmethod
    def _kw_true(call: ast.Call, kwname: str) -> bool:
        for kw in call.keywords:
            if kw.arg == kwname and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return False

    def _thread_fate(self, module, fn, name
                     ) -> Tuple[bool, bool, bool, Optional[str]]:
        started = joined = daemon_later = False
        attr: Optional[str] = None
        for n in module.fn_nodes(fn, subtree=True):
            if isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) and isinstance(
                    n.func.value, ast.Name) and n.func.value.id == name:
                if n.func.attr == "start":
                    started = True
                elif n.func.attr == "join":
                    joined = True
                elif n.func.attr == "setDaemon":
                    daemon_later = True
            elif isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(n.value, ast.Name) and \
                            n.value.id == name:
                        attr = t.attr
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "daemon" and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == name:
                        daemon_later = True
        return started, joined, daemon_later, attr

    @staticmethod
    def _attr_joined(module, attr: str) -> bool:
        """``<anything>.<attr>.join(...)`` anywhere in the module — the
        registered owner's teardown discharges the join obligation."""
        for call in module.all_calls:
            f = call.func
            if isinstance(f, ast.Attribute) and f.attr == "join" and \
                    isinstance(f.value, ast.Attribute) and \
                    f.value.attr == attr:
                return True
        return False

    def _escapes(self, module, fn, name: str) -> bool:
        """The binding leaves the function (returned, stored, passed)."""
        for n in module.fn_nodes(fn, subtree=True):
            if self._node_discharges(module, n, {name}, "thread"):
                return True
        return False

    # --------------------------------------- double release / use-after-free

    def release_events(self, module, fn
                       ) -> List[Tuple[ast.stmt, ast.Call, str, str]]:
        """Statement-level release calls in ``fn``, in source order:
        ``(stmt, call, name, kind_hint)``. Only unconditional statements
        (direct ``Expr`` children of a block) participate — conditional
        releases are path-dependent and stay out of TPU024/TPU025."""
        out: List[Tuple[ast.stmt, ast.Call, str, str]] = []
        for n in module.nodes_by_fn.get(fn, ()):
            if not (isinstance(n, ast.Expr)
                    and isinstance(n.value, ast.Call)):
                continue
            call = n.value
            f = call.func
            if not isinstance(f, ast.Attribute):
                continue
            # N.verb()
            if isinstance(f.value, ast.Name):
                kind = self._verb_kind(f.attr)
                if kind is not None:
                    out.append((n, call, f.value.id, kind))
                    continue
            # owner.release(N) — arg-style (block lists)
            if f.attr == "release":
                for a in call.args:
                    if isinstance(a, ast.Name):
                        out.append((n, call, a.id, "blocks"))
        out.sort(key=lambda t: (t[0].lineno, t[0].col_offset))
        return out

    @staticmethod
    def _verb_kind(attr: str) -> Optional[str]:
        # verbs unique enough to imply a resource kind; `wait`/`join`
        # are idempotent and excluded from the double-release check
        if attr == "close":
            return "socket"                  # socket/file/endpoint family
        if attr == "cleanup":
            return "file"
        if attr == "stamp_terminal":
            return "heartbeat"
        return None

    def double_releases(self, module
                        ) -> Iterator[Tuple[ast.Call, ast.Call, str]]:
        for fn in module.nodes_by_fn:
            events = self.release_events(module, fn)
            seen: Dict[Tuple[int, str], Tuple[ast.stmt, ast.Call]] = {}
            for stmt, call, name, kind in events:
                owner = getattr(stmt, "_gl_parent", None)
                # key on the BLOCK (body vs orelse are different paths
                # through the same If node), not the owning node
                seq = self._containing_block(owner, stmt)
                key = (id(seq) if seq is not None else id(owner), name)
                if key in seen:
                    prev_stmt, prev_call = seen[key]
                    if not self._rebound_between(module, fn, prev_stmt,
                                                 stmt, name):
                        yield prev_call, call, name
                        continue
                seen[key] = (stmt, call)
        return

    def _rebound_between(self, module, fn, a: ast.stmt, b: ast.stmt,
                         name: str) -> bool:
        for n in module.nodes_by_fn.get(fn, ()):
            ln = getattr(n, "lineno", None)
            if ln is None or not (a.lineno < ln <= b.lineno):
                continue
            if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                              ast.Delete)) and self._reassigns(n, name):
                return True
        return False

    def use_after_release(self, module
                          ) -> Iterator[Tuple[ast.Call, ast.AST, str, str]]:
        """``(release_call, use_node, name, verb)`` for a touch of the
        handle after an unconditional release in the same block."""
        for fn in module.nodes_by_fn:
            for stmt, call, name, kind in self.release_events(module, fn):
                owner = getattr(stmt, "_gl_parent", None)
                seq = self._containing_block(owner, stmt)
                if seq is None:
                    continue
                post_ok = POST_RELEASE_OK.get(kind, set()) \
                    | RELEASE_VERBS.get(kind, set())
                for sib in seq[seq.index(stmt) + 1:]:
                    if self._reassigns(sib, name):
                        break
                    use = self._first_active_use(sib, name, post_ok)
                    if use is not None:
                        yield call, use, name, use.func.attr
                        break

    @staticmethod
    def _first_active_use(node: ast.AST, name: str,
                          post_ok: Set[str]) -> Optional[ast.Call]:
        for n in _walk_no_fn(node):
            if isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) and isinstance(
                    n.func.value, ast.Name) and \
                    n.func.value.id == name and \
                    n.func.attr not in post_ok:
                return n
        return None


def get_resource_model(index: ProjectIndex) -> ResourceModel:
    model = getattr(index, "_gl_resource_model", None)
    if model is None:
        model = ResourceModel(index)
        index._gl_resource_model = model
    return model
