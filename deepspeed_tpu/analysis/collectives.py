"""Catalog of every collective entry point the analyzer models.

Three tiers, matched on canonical dotted names (aliases resolve through
the per-module import map, so ``from jax import lax; lax.psum`` and
``jax.lax.psum`` are the same entry):

in-program collectives (``jax.lax``)
    Execute inside a compiled program over named mesh axes. Every
    participant along the axis must execute the same program: a rank
    that never dispatches it wedges the others in the matched collective.

host collectives (``jax.experimental.multihost_utils``)
    Block the calling *process* until every process arrives — the
    sharded-save barrier family. A rank-conditional path around one of
    these is the exact shape of the pre-PR-3 checkpoint hang.

package facade (``deepspeed_tpu.comm``)
    The project's own wrappers (comm/comm.py). Cataloged by dotted name
    so a single-file lint of a caller still knows ``comm.barrier`` is a
    collective even when comm.py itself is outside the lint run; on a
    full-package run the call graph ALSO reaches the ``lax`` calls in
    their bodies, and the two sources agree.
"""

from __future__ import annotations

import ast
from typing import FrozenSet, Optional, Union

#: Sentinel context: "runs under a mesh whose axis names are not
#: statically visible" (axis_names built from a variable, or shard_map
#: deriving axes from a ``mesh=`` object). Rules stay silent rather than
#: guess.
UNKNOWN = "<unknown-axes>"

# canonical name -> index of the axis-name argument (after the tensor)
LAX_COLLECTIVES = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.pshuffle": 1,
}

# axis-consuming but not communicating: validity checked (TPU012), never
# a divergence hazard by itself (TPU011/TPU013 ignore them)
LAX_AXIS_USERS = {
    "jax.lax.axis_index": 0,
    "jax.lax.axis_size": 0,
}

HOST_COLLECTIVES = {
    "jax.experimental.multihost_utils.sync_global_devices",
    "jax.experimental.multihost_utils.broadcast_one_to_all",
    "jax.experimental.multihost_utils.process_allgather",
    "jax.experimental.multihost_utils.assert_equal",
}

# deepspeed_tpu.comm facade: both the defining module's dotted path and
# the package re-export resolve here. Values: axis kwarg semantics like
# the lax table (None = no axis argument).
_FACADE_FNS = {
    "all_reduce": 1, "all_gather": 1, "reduce_scatter": 1,
    "all_to_all": 1, "broadcast": None, "ppermute": 2,
    "send_recv_next": 1, "send_recv_prev": 1, "barrier": None,
}
FACADE_COLLECTIVES = {}
for _name, _pos in _FACADE_FNS.items():
    FACADE_COLLECTIVES[f"deepspeed_tpu.comm.{_name}"] = _pos
    FACADE_COLLECTIVES[f"deepspeed_tpu.comm.comm.{_name}"] = _pos

#: Wrappers that establish a named-axis context for the callable they map
SHARD_WRAPPERS = {"jax.shard_map", "shard_map",
                  "jax.experimental.shard_map.shard_map",
                  # the version-portable wrapper (modern kwargs, legacy
                  # fallback) the comm-plan collectives build through
                  "deepspeed_tpu.utils.jax_compat.shard_map"}
PMAP_WRAPPERS = {"jax.pmap"}

#: Mesh constructors whose axis tuple declares axis names project-wide
MESH_CTORS = {"jax.sharding.Mesh", "Mesh", "jax.make_mesh",
              "jax.interpreters.pxla.Mesh",
              "jax.experimental.mesh_utils.Mesh"}

AXIS_KWARGS = ("axis_name", "axis")


def collective_kind(q: Optional[str]) -> Optional[str]:
    """'lax' / 'host' / 'facade' for a canonical dotted name, else None."""
    if not q:
        return None
    if q in LAX_COLLECTIVES:
        return "lax"
    if q in HOST_COLLECTIVES:
        return "host"
    if q in FACADE_COLLECTIVES:
        return "facade"
    return None


def short_name(q: str) -> str:
    """Display name: last two components ('lax.psum', 'comm.barrier')."""
    parts = q.split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else q


def axis_arg(call: ast.Call, q: str) -> Optional[ast.AST]:
    """The axis-name argument expression of a collective/axis-user call,
    or None when the call has no axis argument (host collectives,
    facade barrier/broadcast without an explicit kwarg)."""
    pos = LAX_COLLECTIVES.get(q, LAX_AXIS_USERS.get(
        q, FACADE_COLLECTIVES.get(q)))
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg in AXIS_KWARGS:
            return kw.value
    return None


def literal_axes(node: Optional[ast.AST]) -> Optional[FrozenSet[str]]:
    """The set of axis names a literal expression denotes: a string, or a
    tuple/list/set of strings. None for non-literal expressions (a
    variable axis is the caller's contract, not this call site's)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        names = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                names.append(e.value)
            else:
                return None
        return frozenset(names)
    return None


AxisContext = Union[FrozenSet[str], str]     # frozenset of names | UNKNOWN
