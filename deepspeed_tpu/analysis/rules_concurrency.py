"""graftlint rules TPU016–TPU021: concurrency safety for the supervision
stack, plus two small contract-sync rules that ride the same sweep.

TPU016–TPU019 consume the lock-and-thread model (locks.py) the way
TPU011–TPU013 consume the call graph: the model resolves lock identity
through self-attrs and imports, discovers thread entries and exit roots,
and propagates held locks through call edges; the rules pattern-match the
four bug shapes every review pass since PR 11 has caught by hand.

TPU020 keeps the chaos failpoint catalog, the docs table and the source
instrumentation in sync; TPU021 keeps the process exit-code contract
single-sourced in ``exit_codes.py``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .. import exit_codes as _ec
from .core import Finding, ModuleInfo, Rule, Severity, register
from .locks import LockModel, get_model
from .rules import UnboundedBlockingRule as _UB


def _model(module: ModuleInfo) -> Optional[LockModel]:
    if module.project is None:
        return None
    return get_model(module.project)


@register
class LockOrderRule(Rule):
    """TPU016 — lock-order inversion anywhere in the project.

    Two locks acquired in opposite nesting orders — directly or through
    any chain of resolvable calls — deadlock the first time the two
    paths interleave: thread 1 holds A and blocks on B while thread 2
    holds B and blocks on A. This is the fleet/handoff/pool shape the
    serving-tier review passes kept checking by hand (the replica lock,
    the handoff mutex and the block-pool mutex each guard a different
    tier and call across tiers). Bounded acquisitions
    (``acquire(timeout=...)``) never create order edges: they fail
    gracefully instead of deadlocking, and the codebase uses exactly
    that idiom (``_replica_down``'s fence) to break cycles on purpose —
    so the FIX for a true inversion is either to swap the nesting or to
    bound one side.

    Each inversion is reported once, anchored on the witness of the
    lexicographically-first direction, citing both acquisition chains
    with file:line so the cycle is reviewable without re-deriving it.
    """

    code = "TPU016"
    name = "lock-order-inversion"
    severity = Severity.ERROR
    summary = "two locks acquired in opposite nesting orders"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = _model(module)
        if model is None:
            return
        for (a, b), w_ab, w_ba in model.inversions():
            m1, node1, qual1, detail1 = w_ab
            m2, node2, qual2, detail2 = w_ba
            if m1 is not module:
                continue        # anchored in the other module's sweep
            yield self.finding(
                module, node1,
                f"lock-order inversion between {model.short(a)} and "
                f"{model.short(b)}: {qual1} holds {model.short(a)} and "
                f"takes {model.short(b)} ({detail1}), but {qual2} at "
                f"{m2.rel_path}:{node2.lineno} holds {model.short(b)} "
                f"and takes {model.short(a)} ({detail2}) — interleaved, "
                f"the two threads deadlock; swap the nesting or bound "
                f"one acquisition with a timeout",
                related=[(m2.rel_path, node2.lineno,
                          f"opposite nesting: {qual2} holds "
                          f"{model.short(b)} and takes {model.short(a)}")])


@register
class BlockingUnderLockRule(Rule):
    """TPU017 — blocking call or device sync while holding a lock.

    A lock held across a jit-compiled step, a ``device_get``/
    ``block_until_ready`` sync, a collective, socket I/O, an opaque
    engine ``.step()``/callback, or a TPU015-class unbounded blocking
    call turns an XLA wedge (or a dead peer) into a held lock — and the
    supervisor that exists to detect the wedge then blocks on that very
    lock. PR 11 fixed exactly this by hand (the fleet worker now steps
    OUTSIDE the replica lock); this rule machine-checks the shape,
    including transitively: a call under the lock whose callee reaches a
    blocking site is cited with the full chain.

    Regions entered through a *bounded* acquire are exempt — the
    codebase's convention is that long-hold locks are only ever taken
    with a timeout by other threads, so a bounded-entry region blocking
    is survivable by design. ``Condition.wait`` on the held lock is also
    exempt (wait releases it). Deliberate long holds (engine warmup,
    donation-discipline device calls) get a suppression with a
    justification, not a redesign.
    """

    code = "TPU017"
    name = "blocking-under-lock"
    severity = Severity.WARNING
    summary = "blocking call or device sync while holding a lock"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = _model(module)
        if model is None:
            return
        index = module.project
        for fn in module.nodes_by_fn:
            if fn is None:
                continue
            acqs = [a for a in model.fn_acqs.get(fn, ())
                    if not a.bounded]
            if not acqs:
                continue
            emitted: Set[str] = set()
            for node in module.nodes_by_fn[fn]:
                if not isinstance(node, ast.Call):
                    continue
                covering = [a for a in acqs
                            if a.lock not in emitted
                            and model.covered(module, a, node)]
                if not covering:
                    continue
                reason = model.blocking_reason(module, node, fn)
                if reason is None:
                    target = index.resolve_call(module, node)
                    if target is not None:
                        below = model.blocking_below(target)
                        if below is not None:
                            rel, ln, qual, why = below
                            reason = (f"a call into {target.qualname}() "
                                      f"that reaches {why} at {rel}:{ln} "
                                      f"(in {qual})")
                if reason is None:
                    continue
                for acq in covering:
                    emitted.add(acq.lock)
                    yield self.finding(
                        module, node,
                        f"{model.short(acq.lock)} (held since line "
                        f"{acq.node.lineno}) is held across {reason}: a "
                        f"wedge there keeps the lock and starves every "
                        f"waiter — move the blocking work outside the "
                        f"lock, or take the lock with a timeout")


@register
class SharedStateRule(Rule):
    """TPU018 — unsynchronized shared mutable state across threads.

    An attribute written from one thread entry's reachable code and read
    or written from a DIFFERENT entry's reachable code, with no lock
    common to both access sites (neither held in-function nor guaranteed
    by every caller), is a data race: torn reads, lost updates, and the
    monitor-thread-reads-stale-status bugs the launcher review passes
    fixed by hand. The rule is a heuristic and says so: it models
    ``self.attr`` and unique-attr receivers only, ignores container
    mutation through method calls, treats all instances of a class as
    one, and trusts the intersection-meet held-lock propagation — so it
    lists its evidence (both sites, both entries, both lock sets) and is
    meant to be suppressed with a justification where the race is
    benign (monotonic flags, single-writer-then-join protocols).

    Attrs holding synchronization primitives or GIL-atomic deques are
    exempt; accesses only reachable from the main thread never conflict
    (two distinct entries are required); one finding per (class, attr).
    """

    code = "TPU018"
    name = "unsynchronized-shared-state"
    severity = Severity.WARNING
    summary = "attr shared across threads with no common lock"

    _INIT_NAMES = ("__init__", "__post_init__")

    def _records(self, model: LockModel) -> Dict[Tuple[str, str], List[dict]]:
        """(class id, attr) -> access records, computed once per run."""
        cached = getattr(model, "_tpu018_records", None)
        if cached is not None:
            return cached
        recs: Dict[Tuple[str, str], List[dict]] = {}
        index = model.index
        for m in index.modules:
            for fn in m.nodes_by_fn:
                if fn is None:
                    continue
                entries = model.entries_reaching.get(fn)
                if not entries:
                    continue        # main-thread-only code never conflicts
                in_init = getattr(fn, "name", "") in self._INIT_NAMES
                held_ctx = model.context_held(fn)
                for node in m.nodes_by_fn[fn]:
                    if not isinstance(node, ast.Attribute) \
                            or not isinstance(node.value, ast.Name):
                        continue
                    if node.value.id == "self":
                        cid = model.fn_class.get(fn)
                    else:
                        cid = model.attr_unique_class.get(node.attr)
                    if cid is None or node.attr not in \
                            model.class_attrs.get(cid, ()):
                        continue
                    if node.attr in model.sync_attrs.get(cid, ()) \
                            or node.attr in model.class_locks.get(cid, {}):
                        continue
                    is_write = isinstance(node.ctx, (ast.Store, ast.Del))
                    locks = model.locks_covering(
                        m, fn, node, include_bounded=True) | held_ctx
                    recs.setdefault((cid, node.attr), []).append({
                        "module": m, "fn": fn, "node": node,
                        "write": is_write, "init": in_init,
                        "entries": entries, "locks": locks,
                    })
        model._tpu018_records = recs
        return recs

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = _model(module)
        if model is None:
            return
        for (cid, attr), recs in sorted(
                self._records(model).items(),
                key=lambda kv: (kv[0][0], kv[0][1])):
            writes = [r for r in recs if r["write"] and not r["init"]]
            if not writes:
                continue
            conflict = None
            for w in writes:
                for r in recs:
                    if r is w:
                        continue
                    if len(w["entries"] | r["entries"]) < 2:
                        continue    # one entry = one thread per instance
                    if w["locks"] & r["locks"]:
                        continue    # a common lock serializes them
                    conflict = (w, r)
                    break
                if conflict:
                    break
            if conflict is None:
                continue
            w, r = conflict
            if w["module"] is not module:
                continue            # anchored in the writing module
            index = model.index

            def _where(rec):
                e = sorted(index.node_of[x].qualname
                           for x in rec["entries"]
                           if x in index.node_of)
                locks = ", ".join(sorted(model.short(x)
                                         for x in rec["locks"])) or "none"
                return (f"{rec['module'].rel_path}:{rec['node'].lineno} "
                        f"(thread entry {'/'.join(e) or '?'}; locks held: "
                        f"{locks})")

            kind = "written" if r["write"] else "read"
            yield self.finding(
                module, w["node"],
                f"{model.short(cid)}.{attr} is written at {_where(w)} "
                f"and {kind} at {_where(r)} with no common lock: threads "
                f"from different entries race on it — guard both sides "
                f"with one lock, or suppress with a justification if the "
                f"race is benign",
                related=[(r["module"].rel_path, r["node"].lineno,
                          f"racing {kind} of {model.short(cid)}.{attr} "
                          f"(no common lock)")])


@register
class ExitPathBlockingRule(Rule):
    """TPU019 — unbounded blocking on an exit path.

    Code reachable from a signal handler, an atexit hook, the watchdog's
    ``_fire``, or any terminal-stamp path runs when the process is
    already dying — often on a thread that interrupted the lock's
    current holder. An unbounded ``acquire()``/``with lock:``, an
    unbounded ``wait``/``join``/``get``, or a call into a bounded-lock
    API *without* its ``lock_timeout=`` turns "exit with diagnostics"
    into a self-deadlock: PR 6's second review pass fixed exactly this
    three times (heartbeat exit paths, the watchdog's terminal stamp,
    the preemption handler's re-acquire). Calls into APIs that expose a
    ``lock_timeout=None`` parameter are autofixable (``--fix`` threads
    ``lock_timeout=5.0`` through); raw ``with``/``acquire`` sites are
    report-only because bounding them changes control flow the author
    must own (what happens when the acquire times out?).
    """

    code = "TPU019"
    name = "exit-path-blocking"
    severity = Severity.WARNING
    summary = "unbounded blocking reachable from an exit path"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = _model(module)
        if model is None:
            return
        index = module.project
        for fn in module.nodes_by_fn:
            if fn is None or fn not in model.exit_reach:
                continue
            root = model.exit_reach[fn]
            seen: Set[ast.AST] = set()
            for acq in model.fn_acqs.get(fn, ()):
                if acq.bounded or acq.node in seen:
                    continue
                seen.add(acq.node)
                how = "with-statement" if acq.kind == "with" \
                    else ".acquire() with no timeout"
                yield self.finding(
                    module, acq.node,
                    f"unbounded {how} on {model.short(acq.lock)} on an "
                    f"exit path (reachable from {root}): if the holder "
                    f"is the wedged code this exit is escaping, the exit "
                    f"deadlocks — acquire with a timeout and degrade to "
                    f"exiting without the protected work")
            for node in module.nodes_by_fn[fn]:
                if not isinstance(node, ast.Call) or node in seen:
                    continue
                f = node.func
                if isinstance(f, ast.Attribute):
                    recv = _UB._receiver(f)
                    flagged = None
                    if f.attr == "join" and not node.args \
                            and not node.keywords:
                        flagged = f"{recv or 'thread'}.join()"
                    elif f.attr == "acquire" and not _UB._bounded(node) \
                            and model.resolve_lock_expr(
                                module, f.value, fn) is None \
                            and _UB._LOCKISH.search(recv):
                        flagged = f"{recv}.acquire()"
                    elif f.attr == "wait" and not _UB._bounded(node) \
                            and _UB._EVENTISH.search(recv):
                        flagged = f"{recv}.wait()"
                    elif f.attr == "get" and not _UB._bounded(node) \
                            and _UB._QUEUEISH.search(recv):
                        flagged = f"{recv}.get()"
                    if flagged:
                        seen.add(node)
                        yield self.finding(
                            module, node,
                            f"unbounded {flagged} on an exit path "
                            f"(reachable from {root}): bound it and "
                            f"handle the timed-out case")
                        continue
                target = index.resolve_call(module, node)
                if target is None:
                    continue
                args = getattr(target.fn, "args", None)
                if args is None:
                    continue
                has_param = any(
                    a.arg == "lock_timeout"
                    for a in (list(args.args) + list(args.kwonlyargs)))
                if not has_param:
                    continue
                if any(kw.arg == "lock_timeout" for kw in node.keywords):
                    continue
                seen.add(node)
                yield self.finding(
                    module, node,
                    f"{target.qualname}() called on an exit path "
                    f"(reachable from {root}) without lock_timeout=: "
                    f"the API blocks unboundedly by default — pass "
                    f"lock_timeout= (autofixable with --fix)")


@register
class FailpointCatalogRule(Rule):
    """TPU020 — chaos failpoint name missing from the catalog or docs.

    Every ``failpoint("name")`` / ``chaos.flag("name")`` instrumentation
    site in the package must use a name listed in ``testing/chaos.py``'s
    ``FAILPOINTS`` catalog AND documented in docs/RESILIENCE.md's
    failpoint table — the failpoint analogue of
    ``test_facade_catalog_covers_comm_module``. An undocumented
    failpoint is a resilience hook nobody can discover from the docs; a
    cataloged-but-renamed one silently orphans every chaos test spec
    that armed the old name. The rule is silent when the lint run does
    not include the chaos module (snippet fixtures) and skips the docs
    check when RESILIENCE.md is absent.
    """

    code = "TPU020"
    name = "failpoint-catalog-drift"
    severity = Severity.WARNING
    summary = "failpoint name missing from catalog or docs table"

    _NAME_RE = re.compile(r"`([a-z][a-z0-9_]*\.[a-z0-9_.]+)`")

    def _catalog(self, index) -> Optional[Tuple[Set[str], Optional[Set[str]]]]:
        cached = getattr(index, "_gl_failpoints", False)
        if cached is not False:
            return cached
        out = None
        for m in index.modules:
            if not m.rel_path.endswith("testing/chaos.py"):
                continue
            names: Set[str] = set()
            for node in m.nodes_by_fn.get(None, ()):
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    target, value = node.targets[0].id, node.value
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name) \
                        and node.value is not None:
                    target, value = node.target.id, node.value
                else:
                    continue
                if target == "FAILPOINTS" and isinstance(value, ast.Dict):
                    for k in value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            names.add(k.value)
            documented: Optional[Set[str]] = None
            doc = os.path.join(os.path.dirname(m.path), os.pardir,
                               os.pardir, "docs", "RESILIENCE.md")
            try:
                with open(doc, "r", encoding="utf-8") as fh:
                    documented = set(self._NAME_RE.findall(fh.read()))
            except OSError:
                documented = None
            out = (names, documented)
            break
        index._gl_failpoints = out
        return out

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if module.project is None:
            return
        catalog = self._catalog(module.project)
        if catalog is None:
            return
        names, documented = catalog
        for call in module.all_calls:
            q = module.project.qualify(module, call.func)
            if q is None or not (q.endswith("chaos.failpoint")
                                 or q.endswith("chaos.flag")):
                continue
            if not call.args or not isinstance(call.args[0], ast.Constant) \
                    or not isinstance(call.args[0].value, str):
                continue
            name = call.args[0].value
            if name not in names:
                yield self.finding(
                    module, call,
                    f"failpoint '{name}' is not in testing/chaos.py's "
                    f"FAILPOINTS catalog: add it (with a one-line "
                    f"where-it-fires) so chaos specs and docs can "
                    f"discover it")
            elif documented is not None and name not in documented:
                yield self.finding(
                    module, call,
                    f"failpoint '{name}' is cataloged but missing from "
                    f"docs/RESILIENCE.md's failpoint table: document it "
                    f"so the resilience matrix stays complete")


@register
class ExitCodeLiteralRule(Rule):
    """TPU021 — hardcoded exit-code literal outside ``exit_codes.py``.

    The rc contract (114 preemption / 117 stall / 118 integrity /
    13 chaos kill) is dispatch logic spread across five layers; a raw
    literal that drifts from the constant breaks restart accounting
    silently (a 117 counted as preemption burns no restart budget; a 114
    counted as a crash exhausts it). 114/117/118 are flagged anywhere in
    code (they are contract-reserved values); 13 only in exit-shaped
    contexts (an ``exit``/``_exit`` argument, a comparison against an
    rc-named value, an ``*_EXIT_CODE`` assignment) because a bare 13 is
    usually just a number. Autofixable: ``--fix`` swaps the literal for
    the named constant and imports it from ``deepspeed_tpu.exit_codes``.
    """

    code = "TPU021"
    name = "exit-code-literal"
    severity = Severity.WARNING
    summary = "hardcoded exit-code literal outside the contract module"

    BY_VALUE = {v: n for n, v in (
        ("PREEMPTION_EXIT_CODE", _ec.PREEMPTION_EXIT_CODE),
        ("STALL_EXIT_CODE", _ec.STALL_EXIT_CODE),
        ("INTEGRITY_EXIT_CODE", _ec.INTEGRITY_EXIT_CODE),
        ("KILL_EXIT_CODE", _ec.KILL_EXIT_CODE))}
    _RC_NAME = re.compile(r"^(rc|returncode|exit_?code|code)$", re.I)
    _EXIT_FNS = {"exit", "_exit", "exit_fn"}

    def _exit_context(self, module: ModuleInfo, node: ast.AST) -> bool:
        parent = module.parent(node)
        if isinstance(parent, ast.Call):
            f = parent.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if name in self._EXIT_FNS:
                return True
        if isinstance(parent, ast.Compare):
            for other in [parent.left] + list(parent.comparators):
                if other is node:
                    continue
                name = other.attr if isinstance(other, ast.Attribute) \
                    else (other.id if isinstance(other, ast.Name) else "")
                if self._RC_NAME.match(name or ""):
                    return True
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                if isinstance(t, ast.Name) and t.id.endswith("_EXIT_CODE"):
                    return True
        return False

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if module.rel_path.endswith("exit_codes.py"):
            return
        for node in module.all_nodes:
            if not isinstance(node, ast.Constant) \
                    or type(node.value) is not int \
                    or node.value not in self.BY_VALUE:
                continue
            parent = module.parent(node)
            if isinstance(parent, ast.UnaryOp):
                continue            # -13 is a signal rc, not the contract
            if node.value == 13 and not self._exit_context(module, node):
                continue
            name = self.BY_VALUE[node.value]
            yield self.finding(
                module, node,
                f"hardcoded exit-code literal {node.value}: the rc "
                f"contract is single-sourced — use "
                f"deepspeed_tpu.exit_codes.{name} (autofixable with "
                f"--fix)")
