"""graftlint — JAX/TPU-aware static analysis that gates the hot path.

AST-only (never imports the linted code), so a full-package pass is
CI-cheap. Rules TPU001–TPU010 target the per-module bug classes that
silently regress the gas-amortized train step: host syncs, retraces,
trace-time side effects, dtype leaks, missing donation, tracer
branches, PRNG key reuse, sharding-spec drift, scan-carry widening and
unscoped kernels. TPU011–TPU013 are INTERPROCEDURAL: a project-wide
call graph (callgraph.py) + collective catalog (collectives.py) make
rank-divergent collectives, invalid mesh axes and collective-order
divergence visible across function and module boundaries — the
distributed-hang class PRs 3–4 fixed at runtime. TPU016–TPU019 ride a
lock-and-thread model (locks.py) over the same call graph to catch the
supervision-stack deadlock shapes (lock-order inversion, blocking under
a lock, unsynchronized shared state, unbounded blocking on exit paths);
TPU020/TPU021 keep the chaos-failpoint catalog and the exit-code
contract in sync with their single sources. TPU022–TPU025 ride a
resource-lifecycle model (resources.py) that proves every acquired
pool block, socket, subprocess, thread, heartbeat file and ``.tmp``
staging dir is released on every failure path — leaks on exception or
chaos-failpoint paths, unjoined non-daemon threads, double-release and
use-after-release. ``--fix`` autofixes the
mechanical rules; ``--sarif`` emits SARIF 2.1.0 for CI PR annotation;
``--timing`` prints the per-rule runtime budget. See docs/LINT.md for
the catalog, architecture and workflows.

Programmatic use::

    from deepspeed_tpu.analysis import lint_paths, RULES
    findings = lint_paths(["deepspeed_tpu/"])
"""

from . import rules as _rules  # noqa: F401  (registers TPU001–TPU010)
from . import rules_collective as _rules2  # noqa: F401  (TPU011–TPU013)
from . import rules_concurrency as _rules3  # noqa: F401  (TPU016–TPU021)
from . import rules_resources as _rules4  # noqa: F401  (TPU022–TPU025)
from .baseline import Baseline, DEFAULT_BASELINE
from .callgraph import ProjectIndex
from .cli import main
from .core import (Finding, ModuleInfo, Rule, RULES, Severity, lint_modules,
                   lint_paths)

__all__ = ["Baseline", "DEFAULT_BASELINE", "Finding", "ModuleInfo",
           "ProjectIndex", "Rule", "RULES", "Severity", "lint_modules",
           "lint_paths", "main"]
