"""graftlint — JAX/TPU-aware static analysis that gates the hot path.

AST-only (never imports the linted code), so a full-package pass is
CI-cheap. Rules TPU001–TPU007 target the bug classes that silently
regress the gas-amortized train step: host syncs, retraces, trace-time
side effects, dtype leaks, missing donation, tracer branches and PRNG
key reuse. See docs/LINT.md for the catalog and workflow.

Programmatic use::

    from deepspeed_tpu.analysis import lint_paths, RULES
    findings = lint_paths(["deepspeed_tpu/"])
"""

from . import rules as _rules  # noqa: F401  (registers TPU001–TPU007)
from .baseline import Baseline, DEFAULT_BASELINE
from .cli import main
from .core import Finding, ModuleInfo, Rule, RULES, Severity, lint_paths

__all__ = ["Baseline", "DEFAULT_BASELINE", "Finding", "ModuleInfo", "Rule",
           "RULES", "Severity", "lint_paths", "main"]
