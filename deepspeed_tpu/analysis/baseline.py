"""Checked-in baseline: accepted findings that don't gate the build.

Every entry carries a one-line ``justification`` — a baseline is a debt
ledger, not a mute button. Matching is positional-churn-proof: entries
bind to (rule, path, enclosing symbol, normalized line text), not line
numbers, so reformatting elsewhere in the file doesn't invalidate them.
Stale entries (matching nothing anymore) are reported so the ledger
shrinks as debts are paid.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from .core import Finding

DEFAULT_BASELINE = ".graftlint.json"


class Baseline:
    def __init__(self, entries: Optional[List[dict]] = None,
                 path: Optional[str] = None):
        self.path = path
        self.entries = entries or []
        self._index: Dict[Tuple[str, str, str, str], dict] = {}
        for e in self.entries:
            self._index[self._entry_key(e)] = e
        self._matched: set = set()

    @staticmethod
    def _entry_key(e: dict) -> Tuple[str, str, str, str]:
        return (e.get("rule", ""), e.get("path", ""), e.get("symbol", ""),
                " ".join(e.get("line_text", "").split()))

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        return cls(entries=data.get("findings", []), path=path)

    def apply(self, findings: List[Finding]) -> None:
        """Mark findings covered by the baseline (and remember which
        entries matched, for staleness reporting)."""
        for f in findings:
            e = self._index.get(f.key())
            if e is not None:
                f.baselined = True
                f.justification = e.get("justification", "")
                self._matched.add(self._entry_key(e))

    def stale_entries(self) -> List[dict]:
        return [e for e in self.entries
                if self._entry_key(e) not in self._matched]

    @staticmethod
    def write(path: str, findings: List[Finding]) -> int:
        """Snapshot the current gating findings as the new baseline.
        Existing justifications are preserved for entries that survive."""
        old = Baseline.load(path)
        kept: List[dict] = []
        seen = set()
        for f in findings:
            if f.suppressed or f.severity.name == "INFO":
                continue
            key = f.key()
            if key in seen:
                continue
            seen.add(key)
            prev = old._index.get(key, {})
            kept.append({
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "line_text": f.line_text,
                "justification": prev.get(
                    "justification",
                    "TODO: justify or fix (added by --write-baseline)"),
            })
        kept.sort(key=lambda e: (e["path"], e["rule"], e["symbol"]))
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "tool": "graftlint",
                       "findings": kept}, f, indent=2)
            f.write("\n")
        return len(kept)
