"""graftlint CLI: ``python -m deepspeed_tpu.analysis`` / ``bin/graftlint``.

Exit codes: 0 clean (or fully baselined/suppressed), 1 gating findings,
2 usage error. The baseline defaults to ``.graftlint.json`` next to the
linted tree's repo root (first ancestor of the first path that has one),
so CI and a bare ``bin/graftlint deepspeed_tpu/`` agree on what's
accepted.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import rules as _rules  # noqa: F401  (imports register TPU001–010)
from . import rules_collective as _rules2  # noqa: F401  (TPU011–013)
from . import rules_concurrency as _rules3  # noqa: F401  (TPU016–021)
from . import rules_resources as _rules4  # noqa: F401  (TPU022–025)
from .baseline import Baseline, DEFAULT_BASELINE
from .core import RULES, Severity, lint_paths
from .reporters import (report_json, report_rules, report_sarif,
                        report_text, write_sarif)


def _find_baseline(paths: List[str]) -> Optional[str]:
    start = os.path.abspath(paths[0]) if paths else os.getcwd()
    cur = start if os.path.isdir(start) else os.path.dirname(start)
    while True:
        cand = os.path.join(cur, DEFAULT_BASELINE)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def _parse_codes(s: str) -> set:
    codes = {c.strip().upper() for c in s.split(",") if c.strip()}
    unknown = codes - set(RULES)
    if unknown:
        raise argparse.ArgumentTypeError(
            f"unknown rule(s): {', '.join(sorted(unknown))} "
            f"(known: {', '.join(sorted(RULES))})")
    return codes


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX/TPU-aware static analysis for deepspeed_tpu")
    p.add_argument("paths", nargs="*", default=None,
                   help="files or directories (default: deepspeed_tpu/)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--sarif", metavar="PATH",
                   help="additionally write a SARIF 2.1.0 report to PATH "
                        "(for CI PR annotation), regardless of --format")
    p.add_argument("--fix", action="store_true",
                   help="apply autofixes for the mechanical rules "
                        "(TPU008 spec canonicalization, TPU010 "
                        "named_scope wrapping), then re-lint")
    p.add_argument("--baseline", metavar="PATH",
                   help=f"baseline file (default: nearest {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept current findings into the baseline and exit")
    p.add_argument("--select", "--rules", type=_parse_codes,
                   metavar="CODES", dest="select",
                   help="run only these rules (comma-separated); "
                        "--rules is an alias for targeted runs")
    p.add_argument("--ignore", "--exclude-rules", type=_parse_codes,
                   metavar="CODES", dest="ignore",
                   help="skip these rules; --exclude-rules is an alias")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed/baselined findings")
    p.add_argument("--strict", action="store_true",
                   help="INFO findings gate too")
    p.add_argument("--timing", action="store_true",
                   help="print per-rule wall time to stderr (slowest "
                        "first) — the analyzer-runtime budget gate")
    p.add_argument("--list-rules", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        report_rules()
        return 0

    paths = args.paths
    if not paths:
        default = os.path.join(os.getcwd(), "deepspeed_tpu")
        paths = [default] if os.path.isdir(default) else ["."]
    for p in paths:
        if not os.path.exists(p):
            print(f"graftlint: no such path: {p}", file=sys.stderr)
            return 2

    # finding paths must be relative to the baseline's directory (the repo
    # root), not the cwd — otherwise running graftlint from elsewhere
    # breaks every baseline match
    baseline_path = args.baseline or _find_baseline(paths)
    root = os.path.dirname(os.path.abspath(baseline_path)) \
        if baseline_path else os.getcwd()

    if args.fix:
        from .fixes import fix_paths
        n, files = fix_paths(
            paths, select=args.select, ignore=args.ignore, root=root,
            baseline_path=None if args.no_baseline else baseline_path)
        print(f"graftlint: applied {n} fix(es) in {len(files)} file(s)",
              file=sys.stderr)
        for fpath in files:
            print(f"  fixed {os.path.relpath(fpath, root)}",
                  file=sys.stderr)

    timings: Optional[dict] = {} if args.timing else None
    findings = lint_paths(paths, select=args.select, ignore=args.ignore,
                          root=root, timings=timings)
    if timings is not None:
        total = sum(timings.values())
        print(f"graftlint: timing ({total:.2f}s total)", file=sys.stderr)
        for name, secs in sorted(timings.items(),
                                 key=lambda kv: -kv[1]):
            print(f"  {name:<16} {secs * 1000.0:9.1f} ms",
                  file=sys.stderr)

    if args.write_baseline:
        target = args.baseline or baseline_path or DEFAULT_BASELINE
        n = Baseline.write(target, [f for f in findings if f.gating])
        print(f"graftlint: wrote {n} entries to {target} "
              "(fill in the justifications)", file=sys.stderr)
        return 0

    stale: List[dict] = []
    if baseline_path and not args.no_baseline:
        bl = Baseline.load(baseline_path)
        bl.apply(findings)
        stale = bl.stale_entries()

    if args.sarif:
        write_sarif(args.sarif, findings, stale)
    if args.format == "json":
        report_json(findings, stale)
    elif args.format == "sarif":
        report_sarif(findings, stale)
    else:
        report_text(findings, stale, show_suppressed=args.show_suppressed)

    gate = [f for f in findings if f.gating]
    if args.strict:
        gate += [f for f in findings
                 if f.severity == Severity.INFO
                 and not f.suppressed and not f.baselined]
    return 1 if gate else 0
