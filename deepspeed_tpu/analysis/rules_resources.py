"""graftlint rules TPU022–TPU025: resource-lifecycle safety.

The four rules consume the resource model (resources.py) the way
TPU016–TPU019 consume the lock model: the model catalogs acquire sites
(pool blocks, sockets/endpoints, Popen handles, threads, file handles,
heartbeat writers, checkpoint staging dirs), resolves ownership-transfer
exemptions interprocedurally, and the rules pattern-match the four leak
shapes the chaos matrix only samples:

TPU022  leak-on-exception-path — an acquire whose release is not
        dominated by ``with``/``try-finally``/ownership transfer, so a
        mid-body raise (every keyed chaos failpoint counts) strands it;
TPU023  unjoined non-daemon thread (blocks interpreter shutdown);
TPU024  double-release of the same handle on one straight-line path;
TPU025  use of a handle after its release/close/kill on the same path.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from .core import Finding, ModuleInfo, Rule, Severity, register
from .resources import ResourceModel, get_resource_model


def _rmodel(module: ModuleInfo) -> Optional[ResourceModel]:
    if module.project is None:
        return None
    return get_resource_model(module.project)


@register
class ResourceLeakRule(Rule):
    """TPU022 — resource leaked on an exception (or fall-through) path.

    Every catalogued acquire must be *dominated* by a discharge: a
    ``with`` block, a ``try`` whose handler/finally releases it, an
    ownership transfer (stored on ``self``/a container, returned,
    yielded, handed to a callee that provably discharges its parameter),
    or a plain release before the first raise-capable site. Raise-capable
    means a ``raise``/``assert``, a keyed chaos failpoint (the TPU020
    catalog enumerates exactly the sites the chaos matrix can fire), a
    call that transitively reaches one, or a method call on the fresh
    handle itself. An acquire whose handle is discarded outright
    (``open(p).read()``, a bare ``Popen(...)``) is the degenerate case.

    The fix is one of: move the acquire into a ``with``, wrap the risky
    region in ``try/except``+release, or transfer ownership *before*
    the risky call — never a baseline entry: the gate stays at zero.
    """

    code = "TPU022"
    name = "resource-leak-on-exception-path"
    severity = Severity.WARNING
    summary = "acquired resource not released on every failure path"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = _rmodel(module)
        if model is None:
            return
        for acq in model.acquires_in(module):
            leak = model.check_leak(acq)
            if leak is None:
                continue
            witness, why = leak
            handle = f"'{acq.name}'" if acq.name else "the handle"
            msg = (f"{acq.kind} resource from {acq.how} leaks: {why} "
                   f"— release {handle} in a finally/handler, use "
                   f"'with', or transfer ownership before the risky "
                   f"region")
            related = []
            if witness is not acq.call and witness is not acq.stmt:
                related.append((module.rel_path,
                                getattr(witness, "lineno", acq.stmt.lineno),
                                f"escaping path: {why}"))
            yield self.finding(module, acq.call, msg, related=related)


@register
class UnjoinedThreadRule(Rule):
    """TPU023 — non-daemon thread started but never joined.

    A non-daemon thread nobody joins blocks interpreter shutdown: the
    process wedges in ``threading._shutdown`` exactly where the TPU016
    exit-root machinery proved the teardown path runs. A join counts
    when it is local, performed on the ``self`` attribute the thread was
    registered on (any method of the owning module — the registered
    owner's teardown), or when ownership escapes to a supervisor/ledger.
    ``daemon=True`` (at construction, via ``t.daemon = True`` or
    ``setDaemon``) waives the obligation.
    """

    code = "TPU023"
    name = "unjoined-non-daemon-thread"
    severity = Severity.WARNING
    summary = "non-daemon thread started but joined nowhere"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = _rmodel(module)
        if model is None:
            return
        for call, ctor, attr in model.thread_leaks(module):
            where = (f"stored on self.{attr} but no '.{attr}.join()' "
                     f"exists in this module" if attr else
                     "never joined in the creating function")
            yield self.finding(
                module, call,
                f"non-daemon {ctor} is started but {where} — join it on "
                f"the shutdown path, mark it daemon=True, or hand it to "
                f"a supervisor that joins it")


@register
class DoubleReleaseRule(Rule):
    """TPU024 — the same handle released twice on one path.

    Two unconditional releases of one binding in the same statement
    block, with no rebind between: the second is dead at best
    (``close()``) and a crash at worst (``BlockPool.release`` raises
    ``ValueError`` on an unallocated id, so a double block release takes
    down the serving loop that was supposed to be recovering). Guarded
    or cross-branch releases are out of scope — only straight-line
    duplicates are certain enough to gate. Popen ``terminate→wait→kill``
    escalation chains are exempt by catalog.
    """

    code = "TPU024"
    name = "double-release"
    severity = Severity.ERROR
    summary = "same handle released twice on one straight-line path"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = _rmodel(module)
        if model is None:
            return
        for first, second, name in model.double_releases(module):
            yield self.finding(
                module, second,
                f"'{name}' is already released at line {first.lineno}; "
                f"this second release on the same path is dead code or "
                f"a crash (refcounted pools raise on double release)",
                related=[(module.rel_path, first.lineno,
                          f"first release of '{name}'")])


@register
class UseAfterReleaseRule(Rule):
    """TPU025 — handle used after its release on the same path.

    Touching a socket/endpoint after ``close()``, a file after
    ``close()``, or forking released pool blocks is at best an
    ``OSError`` at the worst moment and at worst silent corruption (a
    released block id may already belong to another sequence). Per-kind
    vocabularies keep the reaping idioms quiet: ``poll``/``wait`` after
    ``kill`` is how a Popen is reaped; a second ``close`` is TPU024's
    business, not this rule's.
    """

    code = "TPU025"
    name = "use-after-release"
    severity = Severity.ERROR
    summary = "handle used after release/close/kill on the same path"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = _rmodel(module)
        if model is None:
            return
        for release, use, name, verb in model.use_after_release(module):
            yield self.finding(
                module, use,
                f"'{name}.{verb}()' after '{name}' was released at line "
                f"{release.lineno} — the handle is dead on this path; "
                f"reorder the use or re-acquire first",
                related=[(module.rel_path, release.lineno,
                          f"'{name}' released here")])
