"""Project-wide call graph and symbol resolution for graftlint.

Per-module analysis (jitscope.py) answers "is this node under a trace?";
this pass answers the questions that need to see the WHOLE lint run at
once:

symbol resolution
    Every def gets a dotted name (``deepspeed_tpu.comm.comm.barrier``,
    ``...checkpoint.engine.TorchCheckpointEngine.save``). Imports —
    including RELATIVE imports, which jitscope ignores — map local names
    onto those dotted names, and one level of re-export indirection is
    followed (``from .comm import barrier`` in ``comm/__init__.py`` makes
    ``deepspeed_tpu.comm.barrier`` an alias of the real def), so a call
    through any spelling resolves to the same FunctionNode.

call edges
    Bare-name calls resolve to same-module defs; ``self.meth()`` to
    methods of the enclosing class; dotted calls through the import map
    to defs in OTHER modules of the same lint run.

rank guards
    ``if jax.process_index() == 0:`` / ``if comm.get_rank() != 0:`` /
    ``if rank == 0:`` (name matched, or a local assigned from a rank
    probe) mark their body AND orelse as rank-divergent: only some
    processes execute them. World-size probes (``process_count``,
    ``get_world_size``) are uniform across ranks and are NOT guards.

collective reachability
    For each function, the set of collectives (see collectives.py)
    reachable through UNGUARDED calls — the payload TPU011 checks when a
    call site sits under a rank guard, so "rank 0 calls a helper whose
    helper calls barrier()" is caught the same as a direct barrier.

axis contexts
    For each function, the named-axis sets it can run under: direct
    ``shard_map``/``pmap`` wraps where this function (or a lambda) is the
    mapped callable, propagated through call edges and lexical nesting.
    Contexts whose axis names aren't statically visible are UNKNOWN and
    make TPU012 stay silent rather than guess.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from . import collectives as C

_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: canonical dotted names whose call result is THIS process's rank
RANK_PROBES = {
    "jax.process_index",
    "deepspeed_tpu.comm.get_rank", "deepspeed_tpu.comm.comm.get_rank",
    "deepspeed_tpu.comm.get_local_rank",
    "deepspeed_tpu.comm.comm.get_local_rank",
}
#: bare attribute/function names that read as a rank probe even when the
#: receiver can't be resolved (``_jax.process_index()``, ``dist.get_rank()``)
_RANK_CALL_ATTRS = {"process_index", "get_rank", "get_local_rank"}
#: identifiers that denote a rank by convention (params, locals, attrs)
_RANK_NAME = re.compile(
    r"^(?:global_|local_|node_)?rank$|^process_index$|^process_id$")


def _locally_bound(module, name_node: ast.Name) -> bool:
    """True when ``name_node``'s identifier is bound by any enclosing
    function — a parameter, assignment/annotated-assignment target,
    aug-assignment, for-loop target, with-item alias, or walrus. Such a
    use reads the LOCAL binding, never the module-level constant."""
    ident = name_node.id
    fn = module.enclosing_function(name_node)
    while fn is not None:
        args = getattr(fn, "args", None)
        if args is not None:
            for a in (list(args.args) + list(args.posonlyargs)
                      + list(args.kwonlyargs)
                      + [x for x in (args.vararg, args.kwarg) if x]):
                if a.arg == ident:
                    return True
        for node in module.nodes_by_fn.get(fn, ()):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                                   ast.NamedExpr)):
                targets = [node.target]
            elif isinstance(node, ast.For):
                targets = [node.target]
            elif isinstance(node, ast.comprehension):
                targets = [node.target]
            elif isinstance(node, ast.withitem) and node.optional_vars:
                targets = [node.optional_vars]
            for t in targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name) and leaf.id == ident:
                        return True
        fn = module.enclosing_function(fn)
    return False


def module_dotted_name(rel_path: str) -> str:
    """'deepspeed_tpu/comm/comm.py' -> 'deepspeed_tpu.comm.comm';
    '__init__.py' collapses onto its package."""
    p = rel_path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [x for x in p.split("/") if x and x != "."]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    # paths escaping the root (lint of /tmp fixtures from elsewhere):
    # fall back to the basename so names stay valid, if not unique
    parts = [x for x in parts if x != ".."]
    return ".".join(parts)


class FunctionNode:
    """One def (or lambda) in the project."""

    __slots__ = ("module", "fn", "qualname", "dotted")

    def __init__(self, module, fn: ast.AST, qualname: str, dotted: str):
        self.module = module
        self.fn = fn
        self.qualname = qualname
        self.dotted = dotted

    def __repr__(self):
        return f"<fn {self.dotted}>"


class ProjectIndex:
    """Symbol table + call graph over every module in one lint run."""

    def __init__(self, modules: List):
        self.modules = list(modules)
        self.mod_dotted: Dict[int, str] = {}     # id(module) -> dotted
        self._is_init: Dict[int, bool] = {}
        self.by_dotted: Dict[str, FunctionNode] = {}
        self.node_of: Dict[ast.AST, FunctionNode] = {}
        self._aliases: Dict[int, Dict[str, str]] = {}   # per-module imports
        self._reexports: Dict[str, str] = {}            # dotted -> dotted
        self._reach: Dict[ast.AST, Dict[str, Tuple[str, int, str]]] = {}
        self._ctx_memo: Dict[ast.AST, List[C.AxisContext]] = {}
        self._callers: Dict[ast.AST, List[ast.AST]] = {}
        self._direct_ctx: Dict[ast.AST, List[C.AxisContext]] = {}
        self.axis_universe: Set[str] = set()
        #: dotted constant name -> axis-name set it denotes (module-level
        #: ``NAME = "model"`` / ``AXES = ("data", "model")`` assignments);
        #: None marks a name assigned CONFLICTING literals (never guess)
        self.axis_constants: Dict[str, Optional[FrozenSet[str]]] = {}
        #: dotted constant name -> (module, P(...) call) for module-level
        #: ``SPEC = PartitionSpec(...)`` assignments; None marks a name
        #: reassigned or non-literal (poisoned — never guess which
        #: assignment is live). Feeds TPU008's constant resolution the
        #: way axis_constants feeds TPU012's.
        self.spec_constants: Dict[str, Optional[Tuple]] = {}
        self._rank_locals: Dict[ast.AST, Set[str]] = {}
        for m in self.modules:
            self._register_module(m)
        for m in self.modules:
            self._collect_imports(m)
        for m in self.modules:
            self._collect_axis_constants(m)
        for m in self.modules:
            self._collect_spec_constants(m)
        for m in self.modules:
            self._collect_contexts_and_axes(m)
        for m in self.modules:
            self._collect_callers(m)

    # ------------------------------------------------------------- building

    def _register_module(self, module) -> None:
        dotted = module_dotted_name(module.rel_path)
        self.mod_dotted[id(module)] = dotted
        self._is_init[id(module)] = module.rel_path.endswith("__init__.py")
        for fn in module.scope._defs:
            if isinstance(fn, ast.Lambda):
                node = FunctionNode(module, fn, "<lambda>",
                                    f"{dotted}.<lambda>@{fn.lineno}")
            else:
                qual = module.enclosing_qualname(fn)
                node = FunctionNode(module, fn, qual, f"{dotted}.{qual}")
                self.by_dotted.setdefault(node.dotted, node)
            self.node_of[fn] = node

    def _package_base(self, module, level: int) -> List[str]:
        parts = self.mod_dotted[id(module)].split(".")
        if not self._is_init[id(module)]:
            parts = parts[:-1]
        drop = level - 1
        return parts[:len(parts) - drop] if drop else parts

    def _collect_imports(self, module) -> None:
        """Local name -> dotted prefix, ABSOLUTE and RELATIVE imports both
        (jitscope's ImportMap skips relative ones; the call graph cannot)."""
        table: Dict[str, str] = dict(module.scope.imports.aliases)
        mod_dotted = self.mod_dotted[id(module)]
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.level > 0:
                base = self._package_base(module, node.level)
                prefix = ".".join(base + ([node.module] if node.module
                                          else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    table[a.asname or a.name] = f"{prefix}.{a.name}"
        self._aliases[id(module)] = table
        # re-export edges: `from X import y as z` makes <module>.z an
        # alias of X.y for OTHER modules importing through this one
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                if node.level > 0:
                    base = self._package_base(module, node.level)
                    src = ".".join(base + ([node.module] if node.module
                                           else []))
                elif node.module:
                    src = node.module
                else:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    self._reexports[f"{mod_dotted}.{a.asname or a.name}"] \
                        = f"{src}.{a.name}"

    def qualify(self, module, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain through this module's
        FULL import table (absolute + relative)."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        table = self._aliases.get(id(module), {})
        root = table.get(cur.id, cur.id)
        return ".".join([root] + list(reversed(parts)))

    def resolve_dotted(self, dotted: str) -> Optional[FunctionNode]:
        seen = set()
        while dotted not in self.by_dotted and dotted in self._reexports:
            if dotted in seen:
                return None
            seen.add(dotted)
            dotted = self._reexports[dotted]
        return self.by_dotted.get(dotted)

    def resolve_call(self, module, call: ast.Call) -> Optional[FunctionNode]:
        """The project def a call lands on, or None (builtin / external /
        dynamic)."""
        f = call.func
        if isinstance(f, ast.Name):
            target = module.scope.resolve_local_def(f)
            if target is not None:
                return self.node_of.get(target)
            dotted = self._aliases.get(id(module), {}).get(f.id)
            return self.resolve_dotted(dotted) if dotted else None
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                defs = module.scope._by_name.get(f.attr)
                target = self._same_class_def(call, defs) if defs else None
                return self.node_of.get(target) if target else None
            dotted = self.qualify(module, f)
            return self.resolve_dotted(dotted) if dotted else None
        return None

    @staticmethod
    def _enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
        cur = getattr(node, "_gl_parent", None)
        while cur is not None and not isinstance(cur, ast.ClassDef):
            cur = getattr(cur, "_gl_parent", None)
        return cur

    def _same_class_def(self, call: ast.Call,
                        defs: List[ast.AST]) -> Optional[ast.AST]:
        """``self.m()`` resolution among same-named defs: a method of the
        CALLING class wins over a free function or another class's method
        that happens to share the name."""
        cls = self._enclosing_class(call)
        if cls is not None:
            same = [d for d in defs if self._enclosing_class(d) is cls]
            if same:
                return same[-1]
        return defs[-1]

    # ------------------------------------------------------- rank guards

    def _fn_rank_locals(self, module, fn: Optional[ast.AST]) -> Set[str]:
        """Names in ``fn`` carrying rank identity: assigned from a rank
        probe (``p = jax.process_index()``), aliased from another rank
        local (``me = p``), or — the boolean-local depth — assigned a
        BOOLEAN expression over one (``is_master = rank == 0``,
        ``lead = p == 0 and not dry_run``). Boolean-ness is required for
        expression RHSes: ``msg = f"rank {rank}"`` carries a rank-derived
        *value*, not a rank-divergent *predicate*, and treating every
        tainted local as a guard would drown the rule in FPs. Computed to
        a fixpoint so ``is_master = rank == 0; lead = is_master`` chains
        resolve."""
        key = fn if fn is not None else module
        if key in self._rank_locals:
            return self._rank_locals[key]
        names: Set[str] = set()
        assigns = [n for n in module.nodes_by_fn.get(fn, ())
                   if isinstance(n, ast.Assign)]
        changed = True
        while changed:
            changed = False
            for node in assigns:
                if not self._is_rank_rhs(module, node.value, names):
                    continue
                for t in node.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name) and \
                                leaf.id not in names:
                            names.add(leaf.id)
                            changed = True
        self._rank_locals[key] = names
        return names

    def _is_rank_rhs(self, module, value: ast.AST, known: Set[str]) -> bool:
        """Does assigning ``value`` make the target rank-divergent?
        (a) the RHS IS a rank read — a probe call, a rank-named
        name/attribute, or an already-known rank local (plain aliasing);
        (b) the RHS is a boolean expression (Compare/BoolOp/not) that
        READS one anywhere inside."""
        def reads_rank(n: ast.AST) -> bool:
            if isinstance(n, ast.Call):
                return self._is_rank_call(module, n)
            if isinstance(n, ast.Name):
                return bool(_RANK_NAME.match(n.id)) or n.id in known
            if isinstance(n, ast.Attribute):
                return bool(_RANK_NAME.match(n.attr))
            return False

        if reads_rank(value):
            return True
        if isinstance(value, (ast.Compare, ast.BoolOp)) or (
                isinstance(value, ast.UnaryOp)
                and isinstance(value.op, ast.Not)):
            return any(reads_rank(n) for n in ast.walk(value))
        return False

    def _is_rank_call(self, module, call: ast.Call) -> bool:
        q = self.qualify(module, call.func)
        if q in RANK_PROBES:
            return True
        f = call.func
        attr = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        return attr in _RANK_CALL_ATTRS

    def is_rank_test(self, module, test: ast.AST,
                     fn: Optional[ast.AST]) -> bool:
        """Does this condition read the process/rank identity — i.e. can
        it evaluate differently on different ranks of the same job?"""
        rank_locals = self._fn_rank_locals(module, fn)
        for n in ast.walk(test):
            if isinstance(n, ast.Call) and self._is_rank_call(module, n):
                return True
            if isinstance(n, ast.Name) and (
                    _RANK_NAME.match(n.id) or n.id in rank_locals):
                return True
            if isinstance(n, ast.Attribute) and _RANK_NAME.match(n.attr):
                return True
        return False

    def rank_guard(self, module, node: ast.AST) -> Optional[ast.If]:
        """The innermost enclosing ``if`` whose test is rank-divergent
        (searched up to the enclosing function boundary), else None. Both
        arms count: the orelse of ``if rank == 0`` runs on the complement
        set of ranks."""
        fn = module.enclosing_function(node)
        prev, cur = node, module.parent(node)
        while cur is not None and not isinstance(cur, _FN):
            if isinstance(cur, ast.If) and prev is not cur.test and \
                    self.is_rank_test(module, cur.test, fn):
                return cur
            prev, cur = cur, module.parent(cur)
        return None

    # ---------------------------------------------- collective reachability

    def collective_name(self, module, call: ast.Call) -> Optional[str]:
        """Canonical dotted name if this call is a cataloged collective."""
        q = self.qualify(module, call.func)
        if C.collective_kind(q):
            return q
        # a resolved project def that IS a cataloged facade fn (spelled
        # through an alias path the catalog doesn't list)
        target = self.resolve_call(module, call)
        if target is not None and C.collective_kind(target.dotted):
            return target.dotted
        return None

    def direct_collectives(self, module, fn: Optional[ast.AST]
                           ) -> List[Tuple[ast.Call, str, bool]]:
        """(call, canonical name, rank_guarded) for collectives directly
        in ``fn``'s own body (nested defs are their own graph nodes)."""
        out = []
        for node in module.nodes_by_fn.get(fn, ()):
            if isinstance(node, ast.Call):
                q = self.collective_name(module, node)
                if q:
                    out.append((node, q,
                                self.rank_guard(module, node) is not None))
        return out

    def call_edges(self, module, fn: Optional[ast.AST]
                   ) -> List[Tuple[ast.Call, FunctionNode, bool]]:
        out = []
        for node in module.nodes_by_fn.get(fn, ()):
            if isinstance(node, ast.Call):
                target = self.resolve_call(module, node)
                if target is not None and target.fn is not fn:
                    out.append((node, target,
                                self.rank_guard(module, node) is not None))
        return out

    def reachable_collectives(self, node: FunctionNode,
                              _stack: Optional[Set[ast.AST]] = None
                              ) -> Dict[str, Tuple[str, int, str]]:
        """Collectives reachable from ``node`` through UNGUARDED paths:
        {canonical name: (rel_path, line, via-qualname)}. A call or
        collective already under its own rank guard inside a callee is
        conditional there — not part of the callee's unconditional
        contract — so it does not propagate."""
        fn = node.fn
        if fn in self._reach:
            return self._reach[fn]
        stack = _stack if _stack is not None else set()
        if fn in stack:
            return {}
        stack.add(fn)
        out: Dict[str, Tuple[str, int, str]] = {}
        for call, q, guarded in self.direct_collectives(node.module, fn):
            if not guarded and q not in out:
                out[q] = (node.module.rel_path, call.lineno, node.qualname)
        for call, target, guarded in self.call_edges(node.module, fn):
            if guarded:
                continue
            for q, where in self.reachable_collectives(
                    target, stack).items():
                out.setdefault(q, where)
        stack.discard(fn)
        if _stack is None:
            # only memoize top-level walks: an INNER result computed while
            # its caller sits on the cycle stack is truncated at the
            # back-edge and caching it would make later queries
            # order-dependent (a top-level DFS visits every reachable node
            # and accumulates its direct collectives, so it is exact)
            self._reach[fn] = out
        return out

    # ------------------------------------------------------- axis contexts

    def _collect_axis_constants(self, module) -> None:
        """Module-level string/tuple-of-string constants, by dotted name.

        ``MODEL_AXIS = "model"`` makes ``lax.psum(x, MODEL_AXIS)`` — in
        THIS module or any module importing the name — as checkable as
        the literal. A name assigned conflicting literal values is
        poisoned (None): TPU012 stays silent rather than guess which
        assignment is live."""
        dotted = self.mod_dotted[id(module)]
        for node in module.nodes_by_fn.get(None, ()):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target, value = node.target.id, node.value
            else:
                continue
            names = C.literal_axes(value)
            key = f"{dotted}.{target}"
            if names is None:
                # a non-literal reassignment of a known constant poisons it
                if key in self.axis_constants:
                    self.axis_constants[key] = None
                continue
            prev = self.axis_constants.get(key, names)
            self.axis_constants[key] = names if prev == names else None

    #: canonical dotted names that construct a PartitionSpec (kept in
    #: sync with rules.ShardingSpecDriftRule._SPECS)
    SPEC_CTORS = frozenset({"jax.sharding.PartitionSpec",
                            "jax.interpreters.pxla.PartitionSpec"})

    def _collect_spec_constants(self, module) -> None:
        """Module-level ``SPEC = P(...)`` assignments, by dotted name.

        ``QUEUE_SPEC = P("expert", ("data", "seq"))`` makes
        ``with_sharding_constraint(x, QUEUE_SPEC)`` — in this module or
        any importer — as checkable by TPU008 as the inline literal. A
        name reassigned (or assigned a non-PartitionSpec value) is
        poisoned rather than guessed at."""
        dotted = self.mod_dotted[id(module)]
        for node in module.nodes_by_fn.get(None, ()):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target, value = node.target.id, node.value
            else:
                continue
            key = f"{dotted}.{target}"
            is_spec = (isinstance(value, ast.Call) and
                       self.qualify(module, value.func) in self.SPEC_CTORS)
            if not is_spec:
                if key in self.spec_constants:
                    self.spec_constants[key] = None
                continue
            if key in self.spec_constants:     # reassigned: poisoned
                self.spec_constants[key] = None
            else:
                self.spec_constants[key] = (module, value)

    def resolve_spec_constant(self, module, node: ast.AST
                              ) -> Optional[Tuple]:
        """(defining module, P(...) call) for a Name/Attribute that
        denotes a collected module-level PartitionSpec constant; None
        when the name is locally bound (the value is the caller's
        contract), unresolvable, or poisoned."""
        if not isinstance(node, (ast.Name, ast.Attribute)):
            return None
        if isinstance(node, ast.Name) and _locally_bound(module, node):
            return None
        q = self.qualify(module, node)
        if q is None:
            return None
        if isinstance(node, ast.Name) and q == node.id:
            q = f"{self.mod_dotted[id(module)]}.{node.id}"
        seen: Set[str] = set()
        while q not in self.spec_constants and q in self._reexports \
                and q not in seen:
            seen.add(q)
            q = self._reexports[q]
        return self.spec_constants.get(q)

    def resolve_axes(self, module, node: Optional[ast.AST]
                     ) -> Optional[FrozenSet[str]]:
        """:func:`collectives.literal_axes` extended through module-level
        constants: a Name/Attribute (bare local, imported, or re-exported)
        that denotes a collected string/tuple constant resolves to its
        axis set; tuples may MIX literals and constant names. None = not
        statically resolvable (the existing stay-silent contract)."""
        if node is None:
            return None
        names = C.literal_axes(node)
        if names is not None:
            return names

        def one(n: ast.AST) -> Optional[FrozenSet[str]]:
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                return frozenset({n.value})
            if not isinstance(n, (ast.Name, ast.Attribute)):
                return None
            if isinstance(n, ast.Name) and _locally_bound(module, n):
                # a function-local binding (param, assignment, loop
                # target) SHADOWS both module constants AND imported
                # names at this use site: the value is the caller's
                # contract, not the constant's — stay silent
                return None
            q = self.qualify(module, n)
            if q is None:
                return None
            if isinstance(n, ast.Name) and q == n.id:
                # bare, un-imported name: a constant of THIS module
                q = f"{self.mod_dotted[id(module)]}.{n.id}"
            seen: Set[str] = set()
            while q not in self.axis_constants and q in self._reexports \
                    and q not in seen:
                seen.add(q)
                q = self._reexports[q]
            return self.axis_constants.get(q)

        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out: FrozenSet[str] = frozenset()
            for e in node.elts:
                r = one(e)
                if r is None:
                    return None
                out |= r
            return out
        return one(node)

    def _collect_contexts_and_axes(self, module) -> None:
        """Direct shard_map/pmap wraps + the project axis universe."""
        for call in module.all_calls:
            q = self.qualify(module, call.func)
            ctx: Optional[C.AxisContext] = None
            if q in C.SHARD_WRAPPERS:
                ax = next((kw.value for kw in call.keywords
                           if kw.arg == "axis_names"), None)
                names = self.resolve_axes(module, ax)
                ctx = names if names is not None else C.UNKNOWN
            elif q in C.PMAP_WRAPPERS:
                ax = next((kw.value for kw in call.keywords
                           if kw.arg == "axis_name"), None)
                names = self.resolve_axes(module, ax)
                ctx = names if names is not None else C.UNKNOWN
            elif q in C.MESH_CTORS:
                ax = (call.args[1] if len(call.args) > 1 else
                      next((kw.value for kw in call.keywords
                            if kw.arg in ("axis_names", "axis_name")), None))
                names = self.resolve_axes(module, ax)
                if names:
                    self.axis_universe |= names
                continue
            else:
                continue
            if isinstance(ctx, frozenset):
                self.axis_universe |= ctx
            target = None
            if call.args:
                arg = call.args[0]
                target = arg if isinstance(arg, ast.Lambda) else \
                    module.scope.resolve_local_def(arg)
            if target is not None:
                self._direct_ctx.setdefault(target, []).append(ctx)
        # module-level *AXES* tuple constants (parallel/mesh.py MESH_AXES
        # and friends) declare names even when Mesh() is built from them
        for node in module.nodes_by_fn.get(None, ()):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and "AXES" in node.targets[0].id.upper():
                names = C.literal_axes(node.value)
                if names:
                    self.axis_universe |= names

    def _collect_callers(self, module) -> None:
        for fn in list(module.nodes_by_fn):
            for call, target, _g in self.call_edges(module, fn):
                if fn is not None:
                    self._callers.setdefault(target.fn, []).append(fn)

    def axis_contexts(self, fn: ast.AST,
                      _stack: Optional[Set[ast.AST]] = None
                      ) -> List[C.AxisContext]:
        """Every named-axis context ``fn`` can execute under: direct
        wraps, callers' contexts, and the lexically enclosing function's
        contexts (a def nested in a shard_map body runs under its axes)."""
        if fn in self._ctx_memo:
            return self._ctx_memo[fn]
        stack = _stack if _stack is not None else set()
        if fn in stack:
            return []
        stack.add(fn)
        out: List[C.AxisContext] = list(self._direct_ctx.get(fn, ()))
        node = self.node_of.get(fn)
        encl = node.module.enclosing_function(fn) if node else None
        if encl is not None:
            out.extend(self.axis_contexts(encl, stack))
        for caller in self._callers.get(fn, ()):
            out.extend(self.axis_contexts(caller, stack))
        stack.discard(fn)
        if _stack is None:          # only memoize complete computations
            self._ctx_memo[fn] = out
        return out
