"""graftlint core: findings, rule registry, suppressions, module model.

The linter is AST-based and import-free: it never imports the code it
checks (no JAX import, no device initialization), so a full-package pass
is fast enough for CI and pre-commit hooks.

Vocabulary
----------
Rule       a check with a stable code (TPU001..), a default severity and a
           ``check(module)`` generator yielding Findings.
Finding    one violation at (path, line); carries the enclosing function's
           qualname and the stripped source line so baselines survive
           unrelated line-number churn.
Suppression ``# graftlint: disable=TPU001[,TPU002]`` on the offending line
           (or ``disable=all``); ``# graftlint: disable-file=...`` anywhere
           in the file applies file-wide.
"""

from __future__ import annotations

import ast
import dataclasses
import enum
import hashlib
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2

    @property
    def label(self) -> str:
        return self.name.lower()

    @classmethod
    def parse(cls, s: str) -> "Severity":
        return cls[s.upper()]


@dataclasses.dataclass
class Finding:
    rule: str
    severity: Severity
    path: str                      # relative, forward slashes
    line: int
    col: int
    message: str
    symbol: str = "<module>"       # enclosing function qualname
    line_text: str = ""            # stripped source of the offending line
    suppressed: bool = False
    baselined: bool = False
    justification: str = ""        # from the matching baseline entry
    # the AST node the rule anchored to — carried for the autofixers
    # (fixes.py), never serialized
    node: Optional[ast.AST] = dataclasses.field(
        default=None, repr=False, compare=False)
    # secondary sites of a multi-site finding as (path, line, note) —
    # the acquire behind a leak, the second witness of an inversion, an
    # evidence chain; reporters surface them (SARIF relatedLocations)
    related: List[Tuple[str, int, str]] = dataclasses.field(
        default_factory=list, repr=False, compare=False)

    def key(self) -> Tuple[str, str, str, str]:
        """Identity used for baseline matching: stable across pure
        line-number churn (only rule, file, enclosing symbol and the
        normalized line text participate)."""
        return (self.rule, self.path, self.symbol,
                " ".join(self.line_text.split()))

    def fingerprint(self) -> str:
        return hashlib.sha1("\x1f".join(self.key()).encode()).hexdigest()[:12]

    def to_dict(self) -> dict:
        d = {
            "rule": self.rule,
            "severity": self.severity.label,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "symbol": self.symbol,
            "line_text": self.line_text,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "fingerprint": self.fingerprint(),
        }
        if self.related:
            d["related"] = [{"path": p, "line": ln, "note": note}
                            for p, ln, note in self.related]
        return d

    @property
    def gating(self) -> bool:
        """Does this finding fail the run? Suppressed/baselined findings and
        INFO-level notes never gate (INFO can be promoted via --strict)."""
        return (not self.suppressed and not self.baselined
                and self.severity >= Severity.WARNING)


class Rule:
    """Base class; subclasses set ``code``/``name``/``severity``/``summary``
    and implement ``check``. Register with the ``@register`` decorator."""

    code: str = ""
    name: str = ""
    severity: Severity = Severity.WARNING
    summary: str = ""

    def check(self, module: "ModuleInfo") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, module: "ModuleInfo", node: ast.AST, message: str,
                severity: Optional[Severity] = None,
                related: Optional[List[Tuple[str, int, str]]] = None
                ) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(
            rule=self.code,
            severity=self.severity if severity is None else severity,
            path=module.rel_path,
            line=line,
            col=getattr(node, "col_offset", 0),
            message=message,
            symbol=module.enclosing_qualname(node),
            line_text=module.line_text(line),
            node=node,
            related=list(related or []),
        )


RULES: Dict[str, Rule] = {}


def register(cls):
    if not cls.code:
        raise ValueError(f"rule {cls.__name__} has no code")
    if cls.code in RULES:
        raise ValueError(f"duplicate rule code {cls.code}")
    RULES[cls.code] = cls()
    return cls


# --------------------------------------------------------------- suppressions

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


def parse_suppressions(source: str) -> Tuple[Dict[int, Set[str]], Set[str]]:
    """Returns (per-line {lineno: {codes}}, file-wide {codes}); the token
    ``all`` suppresses every rule. A trailing comment suppresses its own
    line; a standalone comment line suppresses the line below it."""
    per_line: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        codes = {c.strip().upper() for c in m.group(2).split(",") if c.strip()}
        if m.group(1) == "disable-file":
            file_wide |= codes
        else:
            target = i + 1 if line.lstrip().startswith("#") else i
            per_line.setdefault(target, set()).update(codes)
    return per_line, file_wide


# ---------------------------------------------------------------- module model

class ModuleInfo:
    """One parsed source file plus everything rules need: parent links,
    qualnames, suppression map, and the jit-scope analysis (attached by the
    runner to avoid a circular import)."""

    def __init__(self, path: str, source: str, rel_path: str):
        self.path = path
        self.rel_path = rel_path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # one pass: parent links, node -> enclosing-function map, and
        # per-function node lists (rules query all three per node; raw
        # ast.walk per rule per function was the lint's dominant cost on
        # a full-package run)
        self._encl: Dict[ast.AST, Optional[ast.AST]] = {}
        self.nodes_by_fn: Dict[Optional[ast.AST], List[ast.AST]] = {None: []}
        self.fn_children: Dict[Optional[ast.AST], List[ast.AST]] = {None: []}
        self.all_nodes: List[ast.AST] = []
        self.all_calls: List[ast.Call] = []
        _FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        stack: List[Tuple[ast.AST, Optional[ast.AST]]] = [(self.tree, None)]
        while stack:
            parent, encl = stack.pop()
            child_encl = parent if isinstance(parent, _FN) else encl
            for child in ast.iter_child_nodes(parent):
                child._gl_parent = parent  # type: ignore[attr-defined]
                self._encl[child] = child_encl
                self.all_nodes.append(child)
                if isinstance(child, ast.Call):
                    self.all_calls.append(child)
                self.nodes_by_fn.setdefault(child_encl, []).append(child)
                if isinstance(child, _FN):
                    self.nodes_by_fn.setdefault(child, [])
                    self.fn_children.setdefault(child, [])
                    self.fn_children.setdefault(child_encl, []).append(child)
                stack.append((child, child_encl))
        self.line_suppressions, self.file_suppressions = \
            parse_suppressions(source)
        from .jitscope import JitScope
        self.scope = JitScope(self)
        # attached by lint_modules(): the project-wide callgraph.ProjectIndex
        # (None when a ModuleInfo is built standalone)
        self.project = None

    # -- navigation -----------------------------------------------------------

    @staticmethod
    def parent(node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_gl_parent", None)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        return self._encl.get(node)

    def fn_nodes(self, fn: Optional[ast.AST],
                 subtree: bool = False) -> Iterator[ast.AST]:
        """Nodes directly owned by ``fn`` (no nested-function bodies); with
        ``subtree=True``, nested-function bodies too."""
        yield from self.nodes_by_fn.get(fn, ())
        if subtree:
            # nested def nodes themselves are direct nodes of the parent;
            # only their bodies need the recursion
            for child in self.fn_children.get(fn, ()):
                yield from self.fn_nodes(child, subtree=True)

    def enclosing_qualname(self, node: ast.AST) -> str:
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                parts.append(cur.name)
            elif isinstance(cur, ast.Lambda):
                parts.append("<lambda>")
            elif isinstance(cur, ast.ClassDef):
                parts.append(cur.name)
            cur = self.parent(cur)
        return ".".join(reversed(parts)) or "<module>"

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def is_suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_suppressions or \
                "ALL" in self.file_suppressions:
            return True
        codes = self.line_suppressions.get(finding.line, set())
        return finding.rule in codes or "ALL" in codes


# --------------------------------------------------------------------- runner

def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    skip_dirs = {".git", "__pycache__", ".pytest_cache", "node_modules",
                 "build", "dist", ".eggs"}
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in skip_dirs)
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


def lint_paths(paths: Iterable[str],
               select: Optional[Set[str]] = None,
               ignore: Optional[Set[str]] = None,
               root: Optional[str] = None,
               timings: Optional[Dict[str, float]] = None) -> List[Finding]:
    """Lint every .py under ``paths``. Returns ALL findings — including
    suppressed ones (marked) so reporters can count them; baseline matching
    happens in the CLI layer."""
    return lint_modules(paths, select=select, ignore=ignore, root=root,
                        timings=timings)[0]


def lint_modules(paths: Iterable[str],
                 select: Optional[Set[str]] = None,
                 ignore: Optional[Set[str]] = None,
                 root: Optional[str] = None,
                 timings: Optional[Dict[str, float]] = None
                 ) -> Tuple[List[Finding], List["ModuleInfo"]]:
    """Two-phase lint. Phase 1 parses EVERY module in the run and builds
    the project-wide call graph / symbol index (callgraph.ProjectIndex) —
    the interprocedural rules (TPU011+) see all of it through
    ``module.project``. Phase 2 runs the rules per module as before.
    Also returns the parsed modules so ``--fix`` can edit them.

    When ``timings`` is given, wall seconds accumulate into it per rule
    code (plus ``<parse+index>`` for phase 1) — the ``--timing`` budget
    gate that keeps the interprocedural passes honest."""
    import time as _time
    root = root or os.getcwd()
    t0 = _time.perf_counter()
    rules = [r for code, r in sorted(RULES.items())
             if (select is None or code in select)
             and (ignore is None or code not in ignore)]
    findings: List[Finding] = []
    modules: List[ModuleInfo] = []
    for fpath in iter_python_files(paths):
        try:
            with open(fpath, "r", encoding="utf-8") as f:
                source = f.read()
            rel = os.path.relpath(os.path.abspath(fpath), root)
            modules.append(ModuleInfo(fpath, source, rel))
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                rule="GL000", severity=Severity.ERROR,
                path=fpath.replace(os.sep, "/"),
                line=getattr(e, "lineno", 1) or 1, col=0,
                message=f"could not parse: {e.__class__.__name__}: {e}"))
    from .callgraph import ProjectIndex
    index = ProjectIndex(modules)
    if timings is not None:
        timings["<parse+index>"] = timings.get("<parse+index>", 0.0) \
            + (_time.perf_counter() - t0)
    for module in modules:
        module.project = index
        for rule in rules:
            t1 = _time.perf_counter() if timings is not None else 0.0
            for finding in rule.check(module):
                finding.suppressed = module.is_suppressed(finding)
                findings.append(finding)
            if timings is not None:
                timings[rule.code] = timings.get(rule.code, 0.0) \
                    + (_time.perf_counter() - t1)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, modules
