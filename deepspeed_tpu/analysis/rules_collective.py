"""graftlint rules TPU011–TPU013: interprocedural collective safety.

All three ride the project-wide call graph (callgraph.py) and the
collective catalog (collectives.py). The bug class: a collective is a
RENDEZVOUS — every participating rank must execute the same sequence of
them — so any code shape that lets SOME ranks skip, reorder, or repeat
one wedges the others in the matched collective until the 117 stall
watchdog fires. PRs 3–4 fixed three live instances of this at runtime
(rank-conditional save paths skipping an allgather, barriers ordered
after rank-0-only publishes, rank-local raises before a barrier); these
rules make the whole class visible before anything runs.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from . import collectives as C
from .core import Finding, ModuleInfo, Rule, Severity, register

_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_EXITS = (ast.Return, ast.Raise, ast.Continue, ast.Break)


def _project(module: ModuleInfo):
    return getattr(module, "project", None)


def _unparse(node: ast.AST, limit: int = 60) -> str:
    s = ast.unparse(node)
    return s if len(s) <= limit else s[:limit - 3] + "..."


@register
class DivergentCollectiveRule(Rule):
    """TPU011 — collective reachable on some ranks only.

    Two shapes, both resolved through the call graph:

    (a) a collective call (or a call to a function whose UNGUARDED body
        transitively reaches one) inside a ``rank == N`` /
        ``process_index()`` branch — only the matching ranks dispatch it;
        everyone else blocks in the matched collective forever.
    (b) a rank-guarded early exit (``if rank != 0: return``) ahead of a
        collective later in the same function — the exiting ranks never
        arrive at the rendezvous.

    World-size probes (``process_count``/``get_world_size``) evaluate the
    same on every rank and are NOT guards — ``comm.barrier``'s own
    ``if jax.process_count() > 1`` gate is the sanctioned idiom.
    """

    code = "TPU011"
    name = "divergent-collective"
    severity = Severity.ERROR
    summary = "collective reachable only under a rank guard"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        index = _project(module)
        if index is None:
            return
        # (a) guarded collective / guarded call reaching one
        for call in module.all_calls:
            guard = index.rank_guard(module, call)
            if guard is None:
                continue
            q = index.collective_name(module, call)
            if q:
                yield self.finding(
                    module, call,
                    f"collective {C.short_name(q)} executes only under "
                    f"rank guard '{_unparse(guard.test)}' (line "
                    f"{guard.lineno}): the other ranks block forever in "
                    "the matched collective. Hoist it out of the guard, "
                    "or guard every rank's matching call identically")
                continue
            target = index.resolve_call(module, call)
            if target is None:
                continue
            reach = index.reachable_collectives(target)
            if reach:
                q, (rpath, rline, rqual) = next(iter(sorted(reach.items())))
                yield self.finding(
                    module, call,
                    f"call to {target.qualname}() under rank guard "
                    f"'{_unparse(guard.test)}' reaches collective "
                    f"{C.short_name(q)} ({rpath}:{rline} in {rqual}): "
                    "only the guarded ranks dispatch it — the rest hang. "
                    "Move the collective out of the rank-conditional path")
        # (b) rank-guarded early exit ahead of a collective
        yield from self._guarded_exits(module, index)

    def _guarded_exits(self, module: ModuleInfo, index) -> Iterator[Finding]:
        for fn in module.scope._defs:
            exits: List[ast.If] = []
            for node in module.nodes_by_fn.get(fn, ()):
                if isinstance(node, ast.If) and index.is_rank_test(
                        module, node.test, fn):
                    for arm in (node.body, node.orelse):
                        if arm and any(isinstance(s, (ast.Return, ast.Raise))
                                       for s in arm):
                            exits.append(node)
                            break
            if not exits:
                continue
            events = _collective_events(module, index, fn)
            for guard in exits:
                after = [e for e in events if e[0].lineno > guard.lineno
                         and index.rank_guard(module, e[0]) is None]
                for call, q, via in after[:1]:    # one finding per guard
                    via_txt = f" (via {via})" if via else ""
                    yield self.finding(
                        module, call,
                        f"collective {C.short_name(q)}{via_txt} is "
                        f"unreachable for ranks taking the early exit "
                        f"under rank guard '{_unparse(guard.test)}' (line "
                        f"{guard.lineno}): the exiting ranks never reach "
                        "the rendezvous. Exit after the collective, or "
                        "make every rank take the same path")


def _collective_events(module: ModuleInfo, index, fn
                       ) -> List[Tuple[ast.Call, str, Optional[str]]]:
    """Source-ordered collective events in ``fn``: direct collective
    calls, plus calls into project functions whose unguarded bodies reach
    one. The third element names the callee for transitive events."""
    events: List[Tuple[ast.Call, str, Optional[str]]] = []
    seen: Set[ast.Call] = set()
    for call, q, _g in index.direct_collectives(module, fn):
        events.append((call, q, None))
        seen.add(call)
    for call, target, _g in index.call_edges(module, fn):
        if call in seen:
            continue
        reach = index.reachable_collectives(target)
        if reach:
            q = next(iter(sorted(reach)))
            events.append((call, q, f"{target.qualname}()"))
    events.sort(key=lambda e: (e[0].lineno, e[0].col_offset))
    return events


@register
class MeshAxisValidityRule(Rule):
    """TPU012 — axis_name not declared by any enclosing mesh context.

    A literal ``axis_name`` handed to a collective must be declared by an
    enclosing ``shard_map``/``pmap``. Resolution is interprocedural: a
    helper that does ``lax.psum(x, "model")`` is checked against the axis
    sets of every shard_map context that reaches it through the call
    graph (or lexically). Axis arguments that are MODULE-LEVEL CONSTANTS
    (round-8 depth) resolve like literals — ``lax.psum(x, MODEL_AXIS)``
    with ``MODEL_AXIS = "model"`` in this or an imported module (the
    parallel/mesh.py idiom), including tuples mixing constants and
    literals; the same resolution feeds shard_map/pmap ``axis_names=``
    declarations, so constant-declared contexts check constant-passed
    axes. A constant assigned conflicting values is never guessed at.
    When no context is statically known, the name is checked against the
    PROJECT axis universe (every axis declared in any shard_map/pmap/
    Mesh/``*_AXES`` constant) — which catches the typo class outright.
    Contexts whose axes aren't statically visible
    (``axis_names={self.axis}``, mesh-derived axes) disable the check
    rather than guess, and the universe fallback is skipped entirely
    when the run declares NO axes (a subset lint of helper files has no
    basis to call anything a typo — full-package runs always have the
    mesh declarations in scope).
    """

    code = "TPU012"
    name = "mesh-axis-validity"
    severity = Severity.ERROR
    summary = "collective axis_name not declared by any enclosing mesh"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        index = _project(module)
        if index is None:
            return
        for call in module.all_calls:
            q = index.qualify(module, call.func)
            if not (C.collective_kind(q) or q in C.LAX_AXIS_USERS):
                continue
            # literals, plus Name/Attribute axis args resolving through
            # module-level constants (``lax.psum(x, MODEL_AXIS)`` with
            # ``MODEL_AXIS = "model"`` in this or an imported module)
            names = index.resolve_axes(module, C.axis_arg(call, q))
            if not names:
                continue
            fn = module.enclosing_function(call)
            ctxs = index.axis_contexts(fn) if fn is not None else []
            if any(c == C.UNKNOWN for c in ctxs):
                continue            # axes not statically visible: no guess
            if ctxs:
                declared = frozenset().union(*ctxs)
                missing = names - declared
                if missing:
                    yield self.finding(
                        module, call,
                        f"axis {sorted(missing)} passed to "
                        f"{C.short_name(q)} is not declared by any "
                        f"shard_map/pmap context reaching this function "
                        f"(declared: {sorted(declared)}): this fails at "
                        "trace time — or resolves against an unintended "
                        "outer mesh")
            elif index.axis_universe:
                # the universe is only judging evidence when this run
                # DECLARES axes: a subset lint (`lint.sh --changed`, a
                # single helper file) that contains no declarations at
                # all has no basis to call anything a typo
                missing = names - index.axis_universe
                if missing:
                    yield self.finding(
                        module, call,
                        f"axis {sorted(missing)} passed to "
                        f"{C.short_name(q)} is not declared anywhere in "
                        f"this lint run (known axes: "
                        f"{sorted(index.axis_universe)}): likely a typo "
                        "for one of the mesh axis names")


@register
class CollectiveOrderRule(Rule):
    """TPU013 — control flow that can reorder or skip paired collectives.

    Ranks must execute the same collective SEQUENCE. Flags (1) a
    conditional ``return``/``raise`` between two collective events in one
    function (a rank-local failure path that exits after collective A but
    before its paired B leaves the other ranks waiting in B — the exact
    pre-PR-3 sharded-save shape, where a rank's write failure raised
    before the allgather), (2) a conditional ``continue``/``break`` ahead
    of a collective in the same loop, and (3) a collective inside a
    ``while`` loop with a data-dependent bound (iteration counts can
    differ per rank, so the collective COUNT diverges). Rank-guarded
    exits are TPU011's domain and are not re-flagged here. The sanctioned
    fix is the ok-flag idiom: catch the local failure, fold it into a
    value every rank contributes to the collective, and act on the
    aggregate afterwards.
    """

    code = "TPU013"
    name = "collective-order-divergence"
    severity = Severity.WARNING
    summary = "conditional exit/loop can desequence paired collectives"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        index = _project(module)
        if index is None:
            return
        for fn in module.scope._defs:
            events = [e for e in _collective_events(module, index, fn)
                      if index.rank_guard(module, e[0]) is None]
            if not events:
                continue
            yield from self._conditional_exits(module, index, fn, events)
            yield from self._while_loops(module, index, fn, events)

    # -- (1)+(2) conditional exits ---------------------------------------

    def _conditional_exits(self, module, index, fn, events
                           ) -> Iterator[Finding]:
        for node in module.nodes_by_fn.get(fn, ()):
            if not isinstance(node, _EXITS):
                continue
            if not self._conditional(module, fn, node):
                continue
            if self._rank_guarded_path(module, index, fn, node):
                continue            # TPU011's shape (b), already reported
            if isinstance(node, (ast.Return, ast.Raise)):
                if isinstance(node, ast.Return) and node.value is not None \
                        and self._returns_collective(module, index, node):
                    # dispatch idiom (comm.all_reduce): `if op == SUM:
                    # return lax.psum(...)` — the exit doesn't SKIP a
                    # collective, it selects which one to execute
                    continue
                before = [e for e in events if e[0].lineno < node.lineno]
                after = [e for e in events if e[0].lineno > node.lineno]
                if before and after:
                    a, b = before[-1], after[0]
                    kind = type(node).__name__.lower()
                    a_via = f" via {a[2]}" if a[2] else ""
                    b_via = f" via {b[2]}" if b[2] else ""
                    yield self.finding(
                        module, node,
                        f"conditional {kind} between paired collectives "
                        f"{C.short_name(a[1])}{a_via} (line {a[0].lineno}) "
                        f"and {C.short_name(b[1])}{b_via} "
                        f"(line {b[0].lineno}): a "
                        "rank taking this exit skips the second "
                        "collective while its peers wait in it. Fold the "
                        "failure into a value all ranks contribute (ok-"
                        "flag idiom) and act on the aggregate")
            else:   # continue / break
                loop = self._enclosing_loop(module, fn, node)
                if loop is None:
                    continue
                in_loop = [e for e in events
                           if self._inside(module, fn, e[0], loop)
                           and e[0].lineno > node.lineno]
                if in_loop:
                    b = in_loop[0]
                    kind = type(node).__name__.lower()
                    yield self.finding(
                        module, node,
                        f"conditional {kind} ahead of collective "
                        f"{C.short_name(b[1])} (line {b[0].lineno}) in "
                        "the same loop: ranks taking it skip that "
                        "iteration's collective and fall out of step "
                        "with their peers")

    @staticmethod
    def _returns_collective(module, index, node: ast.Return) -> bool:
        for n in ast.walk(node.value):
            if isinstance(n, ast.Call):
                if index.collective_name(module, n):
                    return True
                target = index.resolve_call(module, n)
                if target is not None and \
                        index.reachable_collectives(target):
                    return True
        return False

    # -- (3) data-dependent while bounds ---------------------------------

    def _while_loops(self, module, index, fn, events) -> Iterator[Finding]:
        call_locals = self._call_assigned_locals(module, fn)
        for node in module.nodes_by_fn.get(fn, ()):
            if not isinstance(node, ast.While):
                continue
            if not self._data_dependent(node.test, call_locals):
                continue
            inside = [e for e in events
                      if self._inside(module, fn, e[0], node)]
            for call, q, via in inside[:1]:
                via_txt = f" (via {via})" if via else ""
                yield self.finding(
                    module, call,
                    f"collective {C.short_name(q)}{via_txt} inside a "
                    f"while loop with data-dependent bound "
                    f"'{_unparse(node.test)}': ranks whose loop runs a "
                    "different number of iterations execute a different "
                    "collective count and deadlock. Make the trip count "
                    "rank-uniform (reduce the predicate first)")

    # -- helpers ----------------------------------------------------------

    @staticmethod
    def _conditional(module, fn, node) -> bool:
        cur = module.parent(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.If, ast.ExceptHandler, ast.IfExp)):
                return True
            if isinstance(cur, _FN):
                return False
            cur = module.parent(cur)
        return False

    @staticmethod
    def _rank_guarded_path(module, index, fn, node) -> bool:
        return index.rank_guard(module, node) is not None

    @staticmethod
    def _enclosing_loop(module, fn, node):
        cur = module.parent(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.For, ast.While, ast.AsyncFor)):
                return cur
            if isinstance(cur, _FN):
                return None
            cur = module.parent(cur)
        return None

    @staticmethod
    def _inside(module, fn, node, container) -> bool:
        cur = node
        while cur is not None and cur is not fn:
            if cur is container:
                return True
            cur = module.parent(cur)
        return False

    @staticmethod
    def _call_assigned_locals(module, fn) -> Set[str]:
        names: Set[str] = set()
        for node in module.nodes_by_fn.get(fn, ()):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                for t in node.targets:
                    for leaf in ast.walk(t):
                        if isinstance(leaf, ast.Name):
                            names.add(leaf.id)
        return names

    @classmethod
    def _data_dependent(cls, test: ast.AST, call_locals: Set[str]) -> bool:
        """A while bound whose value can differ per rank: the test calls
        something, or references a local produced by a call. ``while
        True`` and pure-parameter bounds are rank-uniform enough."""
        if isinstance(test, ast.Constant):
            return False
        for n in ast.walk(test):
            if isinstance(n, ast.Call):
                return True
            if isinstance(n, ast.Name) and n.id in call_locals:
                return True
        return False
