"""Which code runs under a JAX trace, and which code is the host hot loop.

Everything here is a static over-approximation computed per module:

traced functions
    (a) defs decorated with a tracing wrapper (``@jax.jit``,
        ``@partial(jax.jit, ...)``, ``@jax.remat`` ...);
    (b) defs/lambdas passed by name to a tracing wrapper call
        (``jax.jit(train_step, donate_argnums=(0,))``,
        ``lax.scan(micro_step, ...)``);
    (c) defs nested inside a traced function;
    (d) defs reachable from a traced body through same-module calls
        (``self._finalize_step(...)`` marks method ``_finalize_step``) —
        one fixed point over bare callee names.

hot (step-path) host functions
    functions named in HOT_FUNC_NAMES (the engine's public per-step
    surface) plus any def carrying a ``# graftlint: hotpath`` marker on
    its decorator/def lines. These are NOT traced — they dispatch compiled
    steps — but a host sync inside them stalls the dispatch pipeline the
    same way, so TPU001 checks them at WARNING level.

Aliases are resolved through the module's imports (``import jax.numpy as
jnp`` makes ``jnp.float32`` qualify to ``jax.numpy.float32``), so rules
match on canonical dotted names instead of guessing at spellings.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

# wrappers whose callable argument is traced by JAX (canonical names;
# aliases resolve onto these through the import map)
TRACING_WRAPPERS = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
    "jax.grad", "jax.value_and_grad", "jax.jacfwd", "jax.jacrev",
    "jax.hessian", "jax.vmap", "jax.pmap", "jax.xmap",
    "jax.remat", "jax.checkpoint", "jax.ad_checkpoint.checkpoint",
    "jax.custom_vjp", "jax.custom_jvp", "jax.closure_convert",
    "jax.lax.scan", "jax.lax.cond", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.lax.custom_root",
    "jax.experimental.shard_map.shard_map", "shard_map",
    "jax.experimental.multihost_utils.host_local_array_to_global_array",
    "flax.linen.scan", "flax.linen.remat", "nn.scan", "nn.remat",
}

# wrappers that compile/stage (retrace risk when rebuilt per call) — a
# strict subset of TRACING_WRAPPERS
JIT_WRAPPERS = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit", "jax.pmap",
}

HOT_FUNC_NAMES = {"train_batch", "eval_batch", "forward", "backward", "step"}

_HOTPATH_MARK = re.compile(r"#\s*graftlint:\s*hotpath\b")

# parameters that are static python values by JAX convention even when the
# wrapper's static_argnums can't be resolved statically
CONVENTIONALLY_STATIC = {"train", "training", "is_training", "deterministic",
                         "mode", "axis", "axis_name"}


class ImportMap:
    """local name -> canonical dotted prefix, from the module's imports."""

    def __init__(self, tree: ast.Module):
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.aliases[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def qualify(self, node: ast.AST) -> Optional[str]:
        """Dotted name of a Name/Attribute chain with the root resolved
        through the import table; None for non-name expressions."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.aliases.get(cur.id, cur.id)
        return ".".join([root] + list(reversed(parts)))


def unwrap_partial(call: ast.AST, imports: ImportMap) -> Optional[ast.AST]:
    """``partial(jax.jit, ...)`` -> the ``jax.jit`` node; else None."""
    if isinstance(call, ast.Call) and call.args:
        q = imports.qualify(call.func)
        if q in ("functools.partial", "partial"):
            return call.args[0]
    return None


class JitScope:
    def __init__(self, module):
        self.module = module
        tree = module.tree
        self.imports = ImportMap(tree)
        self._defs: List[ast.AST] = [
            n for n in module.all_nodes
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda))]
        # bare name -> defs (for call-graph propagation)
        self._by_name: Dict[str, List[ast.AST]] = {}
        for d in self._defs:
            if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._by_name.setdefault(d.name, []).append(d)
        self.traced: Set[ast.AST] = set()
        self.static_params: Dict[ast.AST, Set[str]] = {}
        self._traced_effective: Dict[ast.AST, bool] = {}
        self._mark_direct()
        self._propagate_calls()
        self.hot: Set[ast.AST] = {
            d for d in self._defs
            if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
            and d not in self.traced
            and (d.name in HOT_FUNC_NAMES or self._marked_hotpath(d))}

    # -- queries --------------------------------------------------------------

    def wrapper_name(self, call: ast.Call) -> Optional[str]:
        """Canonical wrapper name of a tracing-wrapper Call, else None."""
        q = self.imports.qualify(call.func)
        if q in TRACING_WRAPPERS:
            return q
        return None

    def is_jit_call(self, call: ast.Call) -> bool:
        return self.imports.qualify(call.func) in JIT_WRAPPERS

    def in_traced(self, node: ast.AST) -> bool:
        fn = self.module.enclosing_function(node)
        chain = []
        while fn is not None:
            if fn in self._traced_effective:
                result = self._traced_effective[fn]
                break
            if fn in self.traced:
                result = True
                break
            chain.append(fn)
            fn = self.module.enclosing_function(fn)
        else:
            result = False
        for f in chain:
            self._traced_effective[f] = result
        return result

    def fn_traced(self, fn: ast.AST) -> bool:
        """Is this def effectively traced — marked itself, or nested under
        a traced def?"""
        return fn in self.traced or self.in_traced(fn)

    def in_hot(self, node: ast.AST) -> bool:
        fn = self.module.enclosing_function(node)
        return fn is not None and fn in self.hot

    def static_param_names(self, fn: ast.AST) -> Set[str]:
        return self.static_params.get(fn, set()) | CONVENTIONALLY_STATIC

    def resolve_local_def(self, node: ast.AST) -> Optional[ast.AST]:
        """A Name/Lambda argument -> the local def it references,
        scope-aware: among same-named defs the one visible from the
        reference wins (innermost enclosing scope outward, Python
        name-resolution order), not whichever the module-walk met last —
        two nested helpers both called ``body`` used to collapse onto
        one of them."""
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Name):
            defs = self._by_name.get(node.id)
            if not defs:
                return None
            if len(defs) == 1:
                return defs[0]
            return self._visible_def(node, defs)
        return None

    def _visible_def(self, node: ast.AST, defs: List[ast.AST]) -> ast.AST:
        """Pick among same-named defs by lexical scope: walk the
        reference's enclosing-function chain innermost-out; the first
        scope that directly owns a candidate wins. Within one scope the
        binding live at the reference is the LAST def at or above the
        reference line (rebinding semantics); a forward reference (a
        closure calling a def that appears later) falls back to the
        scope's last def."""
        enc = self.module.enclosing_function
        owner = {d: enc(d) for d in defs}
        scope = enc(node)
        ref_line = getattr(node, "lineno", 0)
        while True:
            cands = [d for d in defs if owner[d] is scope]
            if cands:
                prior = [d for d in cands if d.lineno <= ref_line]
                pool = prior or cands
                return max(pool, key=lambda d: d.lineno)
            if scope is None:
                return defs[-1]
            scope = enc(scope)

    # -- analysis -------------------------------------------------------------

    def _marked_hotpath(self, d: ast.AST) -> bool:
        lines = self.module.lines
        start = min(getattr(dec, "lineno", d.lineno)
                    for dec in ([d] + list(getattr(d, "decorator_list", []))))
        for ln in range(start, d.lineno + 1):
            if 1 <= ln <= len(lines) and _HOTPATH_MARK.search(lines[ln - 1]):
                return True
        return False

    def _decorator_wrapper(self, dec: ast.AST) -> Optional[str]:
        inner = unwrap_partial(dec, self.imports)
        if inner is not None:
            q = self.imports.qualify(inner)
            return q if q in TRACING_WRAPPERS else None
        target = dec.func if isinstance(dec, ast.Call) else dec
        q = self.imports.qualify(target)
        return q if q in TRACING_WRAPPERS else None

    def _record_static(self, fn: ast.AST, call: Optional[ast.Call]):
        """Map static_argnums/static_argnames from a wrapper call onto the
        wrapped def's parameter names (best effort on literal ints/strs)."""
        if call is None or not isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        params = [a.arg for a in fn.args.args]
        names = self.static_params.setdefault(fn, set())
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames"):
                vals = kw.value.elts if isinstance(
                    kw.value, (ast.Tuple, ast.List)) else [kw.value]
                for v in vals:
                    if isinstance(v, ast.Constant):
                        if isinstance(v.value, int) and \
                                0 <= v.value < len(params):
                            names.add(params[v.value])
                        elif isinstance(v.value, str):
                            names.add(v.value)

    def _mark_direct(self):
        # (a) decorated defs
        for d in self._defs:
            for dec in getattr(d, "decorator_list", []):
                if self._decorator_wrapper(dec) is not None:
                    self.traced.add(d)
                    if isinstance(dec, ast.Call):
                        # covers both jit(...) and partial(jit, ...) forms
                        self._record_static(d, dec)
        # (b) defs/lambdas passed to wrapper calls
        for call in self.module.all_calls:
            if self.wrapper_name(call) is None:
                # partial(jax.jit, ...)(fn) style
                inner = unwrap_partial(call.func, self.imports) \
                    if isinstance(call.func, ast.Call) else None
                if inner is None or \
                        self.imports.qualify(inner) not in TRACING_WRAPPERS:
                    continue
            for arg in call.args:
                target = self.resolve_local_def(arg)
                if target is not None:
                    self.traced.add(target)
                    self._record_static(target, call)
        # (c) is implicit: in_traced() walks the enclosing chain

    def _propagate_calls(self):
        # (d) fixed point over bare callee names inside traced bodies
        # (including bodies of defs nested in traced defs — they run under
        # the same trace). Callee names per def are collected once.
        fn_callees: Dict[ast.AST, Set[str]] = {}

        def callees(d: ast.AST) -> Set[str]:
            if d not in fn_callees:
                names: Set[str] = set()
                for n in self.module.fn_nodes(d, subtree=True):
                    if not isinstance(n, ast.Call):
                        continue
                    if isinstance(n.func, ast.Name):
                        names.add(n.func.id)
                    elif isinstance(n.func, ast.Attribute) and isinstance(
                            n.func.value, ast.Name) and \
                            n.func.value.id == "self":
                        names.add(n.func.attr)
                fn_callees[d] = names
            return fn_callees[d]

        worklist = list(self.traced)
        while worklist:
            d = worklist.pop()
            for name in callees(d):
                for target in self._by_name.get(name, []):
                    if target not in self.traced:
                        self.traced.add(target)
                        worklist.append(target)
