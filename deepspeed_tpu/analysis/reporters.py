"""Text and JSON reporters for graftlint findings."""

from __future__ import annotations

import json
import sys
from typing import List

from .core import Finding, RULES, Severity


def summarize(findings: List[Finding]) -> dict:
    gating = [f for f in findings if f.gating]
    return {
        "total": len(findings),
        "gating": len(gating),
        "errors": sum(1 for f in gating if f.severity == Severity.ERROR),
        "warnings": sum(1 for f in gating if f.severity == Severity.WARNING),
        "info": sum(1 for f in findings
                    if f.severity == Severity.INFO and not f.suppressed),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "baselined": sum(1 for f in findings if f.baselined),
    }


def report_text(findings: List[Finding], stale: List[dict],
                show_suppressed: bool = False, stream=None) -> None:
    out = stream or sys.stdout
    shown = [f for f in findings
             if show_suppressed or not (f.suppressed or f.baselined)]
    last_path = None
    for f in shown:
        if f.path != last_path:
            if last_path is not None:
                print(file=out)
            print(f.path, file=out)
            last_path = f.path
        tag = ""
        if f.suppressed:
            tag = " [suppressed]"
        elif f.baselined:
            tag = f" [baselined: {f.justification}]"
        print(f"  {f.line}:{f.col} {f.rule} {f.severity.label}: "
              f"{f.message} ({f.symbol}){tag}", file=out)
    s = summarize(findings)
    if shown:
        print(file=out)
    for e in stale:
        print(f"stale baseline entry: {e['rule']} {e['path']} "
              f"({e['symbol']}) — fixed? remove it from the baseline",
              file=out)
    print(f"graftlint: {s['gating']} gating "
          f"({s['errors']} error, {s['warnings']} warning), "
          f"{s['info']} info, {s['baselined']} baselined, "
          f"{s['suppressed']} suppressed", file=out)


def report_json(findings: List[Finding], stale: List[dict],
                stream=None) -> None:
    out = stream or sys.stdout
    json.dump({
        "version": 1,
        "summary": summarize(findings),
        "findings": [f.to_dict() for f in findings],
        "stale_baseline_entries": stale,
    }, out, indent=2)
    out.write("\n")


_SARIF_LEVELS = {Severity.ERROR: "error", Severity.WARNING: "warning",
                 Severity.INFO: "note"}


def sarif_document(findings: List[Finding], stale: List[dict]) -> dict:
    """SARIF 2.1.0 run for CI PR annotation. Suppressed/baselined
    findings are included WITH a ``suppressions`` entry (SARIF viewers
    hide them but keep the audit trail); a run is "finding-free" when no
    result lacks one."""
    rules_meta = [{
        "id": code,
        "name": rule.name,
        "shortDescription": {"text": rule.summary},
        "defaultConfiguration": {"level": _SARIF_LEVELS[rule.severity]},
    } for code, rule in sorted(RULES.items())]
    results = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "level": _SARIF_LEVELS.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                },
                "logicalLocations": [{"fullyQualifiedName": f.symbol}],
            }],
            "partialFingerprints": {"graftlint/v1": f.fingerprint()},
        }
        if f.related:
            # multi-site findings (TPU016's second nesting site, TPU018's
            # evidence list, TPU022's escaping path, TPU024/025's release
            # site) carry every site: the PR annotation shows the whole
            # story, not just the anchor
            res["relatedLocations"] = [{
                "physicalLocation": {
                    "artifactLocation": {"uri": rp, "uriBaseId": "SRCROOT"},
                    "region": {"startLine": rl},
                },
                "message": {"text": note},
            } for rp, rl, note in f.related]
        if f.suppressed or f.baselined:
            res["suppressions"] = [{
                "kind": "inSource" if f.suppressed else "external",
                "justification": f.justification or
                ("inline graftlint: disable" if f.suppressed else ""),
            }]
        results.append(res)
    invocation = {"executionSuccessful": True}
    if stale:
        invocation["toolExecutionNotifications"] = [{
            "level": "note",
            "message": {"text": f"stale baseline entry: {e['rule']} "
                                f"{e['path']} ({e['symbol']})"},
        } for e in stale]
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                   "master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri":
                    "https://github.com/deepspeed_tpu/docs/LINT.md",
                "rules": rules_meta,
            }},
            "invocations": [invocation],
            "results": results,
        }],
    }


def report_sarif(findings: List[Finding], stale: List[dict],
                 stream=None) -> None:
    out = stream or sys.stdout
    json.dump(sarif_document(findings, stale), out, indent=2)
    out.write("\n")


def write_sarif(path: str, findings: List[Finding],
                stale: List[dict]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        report_sarif(findings, stale, stream=f)


def report_rules(stream=None) -> None:
    out = stream or sys.stdout
    for code, rule in sorted(RULES.items()):
        print(f"{code} [{rule.severity.label}] {rule.name}: {rule.summary}",
              file=out)
