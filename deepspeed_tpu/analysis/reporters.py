"""Text and JSON reporters for graftlint findings."""

from __future__ import annotations

import json
import sys
from typing import List

from .core import Finding, RULES, Severity


def summarize(findings: List[Finding]) -> dict:
    gating = [f for f in findings if f.gating]
    return {
        "total": len(findings),
        "gating": len(gating),
        "errors": sum(1 for f in gating if f.severity == Severity.ERROR),
        "warnings": sum(1 for f in gating if f.severity == Severity.WARNING),
        "info": sum(1 for f in findings
                    if f.severity == Severity.INFO and not f.suppressed),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "baselined": sum(1 for f in findings if f.baselined),
    }


def report_text(findings: List[Finding], stale: List[dict],
                show_suppressed: bool = False, stream=None) -> None:
    out = stream or sys.stdout
    shown = [f for f in findings
             if show_suppressed or not (f.suppressed or f.baselined)]
    last_path = None
    for f in shown:
        if f.path != last_path:
            if last_path is not None:
                print(file=out)
            print(f.path, file=out)
            last_path = f.path
        tag = ""
        if f.suppressed:
            tag = " [suppressed]"
        elif f.baselined:
            tag = f" [baselined: {f.justification}]"
        print(f"  {f.line}:{f.col} {f.rule} {f.severity.label}: "
              f"{f.message} ({f.symbol}){tag}", file=out)
    s = summarize(findings)
    if shown:
        print(file=out)
    for e in stale:
        print(f"stale baseline entry: {e['rule']} {e['path']} "
              f"({e['symbol']}) — fixed? remove it from the baseline",
              file=out)
    print(f"graftlint: {s['gating']} gating "
          f"({s['errors']} error, {s['warnings']} warning), "
          f"{s['info']} info, {s['baselined']} baselined, "
          f"{s['suppressed']} suppressed", file=out)


def report_json(findings: List[Finding], stale: List[dict],
                stream=None) -> None:
    out = stream or sys.stdout
    json.dump({
        "version": 1,
        "summary": summarize(findings),
        "findings": [f.to_dict() for f in findings],
        "stale_baseline_entries": stale,
    }, out, indent=2)
    out.write("\n")


def report_rules(stream=None) -> None:
    out = stream or sys.stdout
    for code, rule in sorted(RULES.items()):
        print(f"{code} [{rule.severity.label}] {rule.name}: {rule.summary}",
              file=out)
