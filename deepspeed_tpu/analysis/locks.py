"""Lock-and-thread model for graftlint's concurrency rules (TPU016–TPU019).

Per-module analysis answers "is this node under a trace?"; the call graph
(callgraph.py) answers "what does this call land on?". This pass answers
the questions the supervision-stack review passes kept re-deriving by
hand since PR 11:

lock identity
    Every statically-visible lock gets a dotted id. Module-level
    ``_lock = threading.Lock()`` assignments are collected the way
    ``spec_constants`` collects ``P(...)`` (single-Name target, poisoned
    on reassignment, resolvable through import/re-export chains);
    ``self._mu = threading.Lock()`` in any method gives a class-scoped id
    (``pkg.mod.Class._mu``) shared by subclasses through base-class
    resolution; an attr that is a lock on exactly ONE project class and
    an attr of no other resolves even through an opaque receiver
    (``rep.lock`` → ``fleet._Replica.lock``). ``_mu``-style attrs owned
    by several classes stay precise through ``self`` and are ambiguous
    (None) through other receivers — the model never guesses.

acquisition facts
    Per function: ``with lock:`` items (region = the statement body plus
    later items' context managers, which run while earlier locks are
    held) and ``lock.acquire()`` calls (region = acquire → the matching
    textual ``.release()`` on the same receiver, else end of function).
    Boundedness reuses TPU015's timeout-slot logic: a bounded acquire
    cannot participate in an unrecoverable deadlock, so bounded
    acquisitions never create order edges or TPU017 regions — but they
    DO count as protection for TPU018 (a successful bounded acquire
    holds the lock).

thread entries / exit roots
    ``threading.Thread(target=...)``, executor ``.submit(fn, ...)``,
    ``signal.signal(sig, handler)`` and ``atexit.register(fn)`` sites,
    resolved to project defs. Signal/atexit handlers — plus watchdog
    ``_fire`` and any ``stamp_terminal`` — are additionally *exit
    roots*: everything reachable from them must obey the bounded
    blocking discipline (TPU019).

propagation
    ``acquired_below`` / ``blocking_below`` walk call edges with the
    same top-level-only memoization as ``reachable_collectives``;
    ``context_held`` runs the classic intersection-meet fixpoint so "this
    helper is only ever called with the replica lock held" is a fact
    rules can use.

Known blind spots (kept deliberately — see docs/LINT.md): calls through
stored objects (``self._handoff.pop()``) do not resolve to defs, so
propagation stops there; lock identity conflates instances of the same
class (sound for ordering, approximate for TPU018); chaos failpoints in
``testing/`` are injection points, not blocking calls, and are excluded
from the blocking walk.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .callgraph import FunctionNode, ProjectIndex, _locally_bound
from .rules import UnboundedBlockingRule as _UB

_FN = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: constructors whose result is a mutual-exclusion object
LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "multiprocessing.Lock", "multiprocessing.RLock",
})

#: constructors whose result is a synchronization primitive or a
#: GIL-atomic container — attrs holding these are never "unsynchronized
#: shared state" (TPU018 skips them)
SYNC_CTORS = LOCK_CTORS | frozenset({
    "threading.Event", "threading.Barrier", "queue.Queue",
    "queue.SimpleQueue", "queue.LifoQueue", "queue.PriorityQueue",
    "collections.deque",
})

#: dotted calls that block without a timeout convention
_BLOCK_QUALS = {
    "jax.device_get": "jax.device_get (device sync)",
    "jax.device_put": "jax.device_put (device transfer)",
    "jax.block_until_ready": "jax.block_until_ready (device sync)",
    "jax.effects_barrier": "jax.effects_barrier (device sync)",
    "time.sleep": "time.sleep",
    "subprocess.run": "subprocess.run",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
}

#: attribute spellings of a device sync (any receiver)
_SYNC_ATTRS = {"block_until_ready", "device_get"}

#: socket/process operations with no bounded variant in this codebase
_IO_ATTRS = {"sendall", "recv", "recv_into", "accept", "connect",
             "communicate"}

#: engine-step attrs: unbounded device work when the receiver is opaque
_ENGINE_ATTRS = {"step", "warm", "run_until_idle"}

#: parameter names that denote an opaque caller-supplied callable
_CB_PARAM = re.compile(r"^(fn|func|callback|exit_fn|on_[a-z_]+|[a-z_]*_fn)$")


class LockAcq:
    """One lock acquisition inside a function body."""

    __slots__ = ("lock", "node", "kind", "item_idx", "bounded", "end_line")

    def __init__(self, lock: str, node: ast.AST, kind: str,
                 item_idx: int = 0, bounded: bool = False,
                 end_line: int = 0):
        self.lock = lock
        self.node = node          # the With statement or the acquire Call
        self.kind = kind          # "with" | "acquire"
        self.item_idx = item_idx
        self.bounded = bounded
        self.end_line = end_line  # acquire-kind only

    def __repr__(self):
        return f"<acq {self.lock} {self.kind}@{self.node.lineno}>"


class LockModel:
    """Project-wide lock/thread facts, built once per lint run and cached
    on the ProjectIndex (see :func:`get_model`)."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        #: dotted lock id -> ctor name, or None when poisoned (reassigned)
        self.module_locks: Dict[str, Optional[str]] = {}
        #: class id -> {attr: lock id}
        self.class_locks: Dict[str, Dict[str, str]] = {}
        #: class id -> every attr assigned through ``self.``
        self.class_attrs: Dict[str, Set[str]] = {}
        #: class id -> attrs assigned a SYNC_CTORS value (TPU018-exempt)
        self.sync_attrs: Dict[str, Set[str]] = {}
        #: class id -> base-class ids resolvable inside the project
        self.class_bases: Dict[str, List[str]] = {}
        #: ast fn -> enclosing class id
        self.fn_class: Dict[ast.AST, str] = {}
        #: attr -> lock id when exactly one project class owns the attr
        #: AND it is a lock there; None marks an ambiguous attr
        self.attr_unique_lock: Dict[str, Optional[str]] = {}
        #: attr -> class id when exactly one project class owns the attr
        self.attr_unique_class: Dict[str, Optional[str]] = {}
        #: ast fn -> [LockAcq] (module-level code holds no locks we model)
        self.fn_acqs: Dict[ast.AST, List[LockAcq]] = {}
        #: ast fn -> how it becomes a thread entry
        self.entries: Dict[ast.AST, str] = {}
        #: ast fn -> why it is an exit root
        self.exit_roots: Dict[ast.AST, str] = {}
        #: ast fn -> set of entry fns whose threads reach it
        self.entries_reaching: Dict[ast.AST, Set[ast.AST]] = {}
        #: ast fn -> qualname of the exit root that reaches it
        self.exit_reach: Dict[ast.AST, str] = {}

        self._below: Dict[ast.AST, Dict[str, Tuple[str, int, str]]] = {}
        self._blocking: Dict[ast.AST, Optional[Tuple[str, int, str, str]]] = {}
        self._edges: Optional[Dict[Tuple[str, str], tuple]] = None
        self._held: Optional[Dict[ast.AST, Optional[FrozenSet[str]]]] = None

        for m in index.modules:
            self._collect_module_locks(m)
        for m in index.modules:
            self._collect_classes(m)
        self._finish_attr_tables()
        for m in index.modules:
            self._collect_acquisitions(m)
        for m in index.modules:
            self._collect_entries(m)
        self._collect_named_roots()
        self._compute_reachability()

    # ------------------------------------------------------------ building

    def _collect_module_locks(self, module) -> None:
        """``_lock = threading.Lock()`` at module level, spec_constants
        style: single Name target, poisoned on reassignment."""
        dotted = self.index.mod_dotted[id(module)]
        for node in module.nodes_by_fn.get(None, ()):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                target, value = node.targets[0].id, node.value
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.value is not None:
                target, value = node.target.id, node.value
            else:
                continue
            key = f"{dotted}.{target}"
            is_lock = (isinstance(value, ast.Call) and
                       self.index.qualify(module, value.func) in LOCK_CTORS)
            if not is_lock:
                if key in self.module_locks:
                    self.module_locks[key] = None   # poisoned
                continue
            if key in self.module_locks:
                self.module_locks[key] = None       # reassigned: poisoned
            else:
                self.module_locks[key] = \
                    self.index.qualify(module, value.func)

    def _collect_classes(self, module) -> None:
        dotted = self.index.mod_dotted[id(module)]
        aliases = self.index._aliases.get(id(module), {})
        for node in module.all_nodes:
            if not isinstance(node, ast.ClassDef):
                continue
            cid = f"{dotted}.{module.enclosing_qualname(node)}"
            attrs = self.class_attrs.setdefault(cid, set())
            locks = self.class_locks.setdefault(cid, {})
            syncs = self.sync_attrs.setdefault(cid, set())
            bases: List[str] = []
            for b in node.bases:
                q = self.index.qualify(module, b)
                if q is None:
                    continue
                if isinstance(b, ast.Name) and q == b.id \
                        and b.id not in aliases:
                    q = f"{dotted}.{b.id}"
                bases.append(q)
            self.class_bases[cid] = bases
            for sub in ast.walk(node):
                if isinstance(sub, _FN):
                    self.fn_class.setdefault(sub, cid)
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(sub, ast.Assign):
                    targets, value = sub.targets, sub.value
                elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                    targets, value = [sub.target], sub.value
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        attrs.add(t.attr)
                        ctor = self.index.qualify(module, value.func) \
                            if isinstance(value, ast.Call) else None
                        if ctor in LOCK_CTORS:
                            locks[t.attr] = f"{cid}.{t.attr}"
                        if ctor in SYNC_CTORS:
                            syncs.add(t.attr)

    def _finish_attr_tables(self) -> None:
        owners: Dict[str, Set[str]] = {}
        lock_owners: Dict[str, Set[str]] = {}
        for cid, attrs in self.class_attrs.items():
            for a in attrs:
                owners.setdefault(a, set()).add(cid)
        for cid, locks in self.class_locks.items():
            for a in locks:
                lock_owners.setdefault(a, set()).add(cid)
        for a, cids in owners.items():
            self.attr_unique_class[a] = next(iter(cids)) \
                if len(cids) == 1 else None
        for a, cids in lock_owners.items():
            if len(cids) == 1 and len(owners.get(a, cids)) == 1:
                cid = next(iter(cids))
                self.attr_unique_lock[a] = self.class_locks[cid][a]
            else:
                self.attr_unique_lock[a] = None

    def class_lock_attr(self, cid: Optional[str], attr: str
                        ) -> Optional[str]:
        """Lock id for ``self.<attr>`` in class ``cid``, walking bases so
        subclasses share the defining class's lock identity."""
        seen: Set[str] = set()
        while cid is not None and cid not in seen:
            seen.add(cid)
            lk = self.class_locks.get(cid, {}).get(attr)
            if lk is not None:
                return lk
            nxt = None
            for b in self.class_bases.get(cid, ()):
                if b in self.class_attrs:
                    nxt = b
                    break
            cid = nxt
        return None

    def resolve_lock_expr(self, module, expr: ast.AST,
                          fn: Optional[ast.AST]) -> Optional[str]:
        """Dotted lock id a Name/Attribute denotes, or None (not a lock
        we know, or ambiguous — the model never guesses)."""
        if isinstance(expr, ast.Name):
            if _locally_bound(module, expr):
                # a function-local ``lock = threading.Lock()``?
                cur = module.enclosing_function(expr)
                while cur is not None:
                    for node in module.nodes_by_fn.get(cur, ()):
                        if isinstance(node, ast.Assign) \
                                and len(node.targets) == 1 \
                                and isinstance(node.targets[0], ast.Name) \
                                and node.targets[0].id == expr.id \
                                and isinstance(node.value, ast.Call) \
                                and self.index.qualify(
                                    module, node.value.func) in LOCK_CTORS:
                            fnode = self.index.node_of.get(cur)
                            qual = fnode.dotted if fnode else "<fn>"
                            return f"{qual}.<local>.{expr.id}"
                    cur = module.enclosing_function(cur)
                return None
            q = self.index.qualify(module, expr)
            if q is None:
                return None
            if q == expr.id:
                q = f"{self.index.mod_dotted[id(module)]}.{expr.id}"
            return self._module_lock(q)
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                return self.class_lock_attr(self.fn_class.get(fn),
                                            expr.attr)
            q = self.index.qualify(module, expr)
            if q is not None:
                lk = self._module_lock(q)
                if lk is not None:
                    return lk
            return self.attr_unique_lock.get(expr.attr)
        return None

    def _module_lock(self, dotted: str) -> Optional[str]:
        seen: Set[str] = set()
        while dotted not in self.module_locks \
                and dotted in self.index._reexports and dotted not in seen:
            seen.add(dotted)
            dotted = self.index._reexports[dotted]
        return dotted if self.module_locks.get(dotted) else None

    def _collect_acquisitions(self, module) -> None:
        for fn in module.nodes_by_fn:
            if fn is None:
                continue
            acqs: List[LockAcq] = []
            for node in module.nodes_by_fn[fn]:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for idx, item in enumerate(node.items):
                        lk = self.resolve_lock_expr(
                            module, item.context_expr, fn)
                        if lk:
                            acqs.append(LockAcq(lk, node, "with", idx))
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "acquire":
                    lk = self.resolve_lock_expr(module, node.func.value, fn)
                    if not lk:
                        continue
                    recv = ast.unparse(node.func.value)
                    end = getattr(fn, "end_lineno", 0) or 10 ** 9
                    for other in module.nodes_by_fn[fn]:
                        if isinstance(other, ast.Call) \
                                and isinstance(other.func, ast.Attribute) \
                                and other.func.attr == "release" \
                                and other.lineno >= node.lineno \
                                and ast.unparse(other.func.value) == recv:
                            end = min(end, other.lineno)
                    acqs.append(LockAcq(lk, node, "acquire",
                                        bounded=_UB._bounded(node),
                                        end_line=end))
            if acqs:
                self.fn_acqs[fn] = acqs

    def _resolve_callable(self, module, expr: ast.AST) -> Optional[ast.AST]:
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Name):
            t = module.scope.resolve_local_def(expr)
            if t is not None:
                return t
            dotted = self.index._aliases.get(id(module), {}).get(expr.id)
            fnode = self.index.resolve_dotted(dotted) if dotted else None
            return fnode.fn if fnode else None
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                defs = module.scope._by_name.get(expr.attr)
                return defs[-1] if defs else None
            dotted = self.index.qualify(module, expr)
            fnode = self.index.resolve_dotted(dotted) if dotted else None
            return fnode.fn if fnode else None
        return None

    def _collect_entries(self, module) -> None:
        for call in module.all_calls:
            q = self.index.qualify(module, call.func)
            if q == "threading.Thread":
                target = next((kw.value for kw in call.keywords
                               if kw.arg == "target"), None)
                fn = self._resolve_callable(module, target) \
                    if target is not None else None
                if fn is not None:
                    self.entries.setdefault(fn, "threading.Thread target")
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "submit" and call.args:
                fn = self._resolve_callable(module, call.args[0])
                if fn is not None:
                    self.entries.setdefault(fn, "executor submit")
            elif q == "signal.signal" and len(call.args) >= 2:
                fn = self._resolve_callable(module, call.args[1])
                if fn is not None:
                    self.entries.setdefault(fn, "signal handler")
                    self.exit_roots.setdefault(fn, "signal handler")
            elif q == "atexit.register" and call.args:
                fn = self._resolve_callable(module, call.args[0])
                if fn is not None:
                    self.exit_roots.setdefault(fn, "atexit handler")

    def _collect_named_roots(self) -> None:
        """Roots the registration sites can't reveal: the watchdog's
        ``_fire`` runs on the watchdog thread when the process is already
        presumed wedged, and any ``stamp_terminal`` is the
        last-words-before-exit path by contract."""
        for fn, fnode in self.index.node_of.items():
            name = getattr(fn, "name", "")
            base = fnode.module.rel_path.rsplit("/", 1)[-1]
            if name == "_fire" and base == "watchdog.py":
                self.exit_roots.setdefault(fn, "watchdog._fire")
            elif name == "stamp_terminal":
                self.exit_roots.setdefault(fn, "terminal stamp path")

    def _reach_from(self, root: ast.AST) -> Set[ast.AST]:
        out: Set[ast.AST] = set()
        stack = [root]
        while stack:
            fn = stack.pop()
            if fn in out:
                continue
            out.add(fn)
            fnode = self.index.node_of.get(fn)
            if fnode is None:
                continue
            for _c, target, _g in self.index.call_edges(fnode.module, fn):
                if target.fn not in out:
                    stack.append(target.fn)
        return out

    def _compute_reachability(self) -> None:
        for entry in self.entries:
            for fn in self._reach_from(entry):
                self.entries_reaching.setdefault(fn, set()).add(entry)
        for root, why in self.exit_roots.items():
            fnode = self.index.node_of.get(root)
            qual = fnode.qualname if fnode else getattr(root, "name", "?")
            for fn in self._reach_from(root):
                self.exit_reach.setdefault(fn, f"{qual} ({why})")

    # ------------------------------------------------------------ coverage

    def covered(self, module, acq: LockAcq, node: ast.AST) -> bool:
        """Does ``node`` execute while ``acq``'s lock is held?"""
        if node is acq.node:
            return False
        if acq.kind == "acquire":
            ln = getattr(node, "lineno", None)
            return ln is not None and \
                (acq.node.end_lineno or acq.node.lineno) < ln <= acq.end_line
        chain: Set[ast.AST] = set()
        cur: Optional[ast.AST] = node
        while cur is not None and cur is not acq.node:
            chain.add(cur)
            cur = module.parent(cur)
        if cur is not acq.node:
            return False
        # inside the With — but items up to and including ours run their
        # context expressions BEFORE this lock is held
        for j in range(acq.item_idx + 1):
            item = acq.node.items[j]
            if item.context_expr is node or item.context_expr in chain:
                return False
        return True

    def locks_covering(self, module, fn: Optional[ast.AST], node: ast.AST,
                       include_bounded: bool = False) -> Set[str]:
        out: Set[str] = set()
        for acq in self.fn_acqs.get(fn, ()):
            if acq.bounded and not include_bounded:
                continue
            if self.covered(module, acq, node):
                out.add(acq.lock)
        return out

    # ------------------------------------------------------- propagation

    def acquired_below(self, fnode: FunctionNode,
                       _stack: Optional[Set[ast.AST]] = None
                       ) -> Dict[str, Tuple[str, int, str]]:
        """Unbounded acquisitions reachable from ``fnode`` (itself
        included): {lock id: (rel_path, line, qualname)}. Top-level-only
        memoization, same reasoning as ``reachable_collectives``."""
        fn = fnode.fn
        if fn in self._below:
            return self._below[fn]
        stack = _stack if _stack is not None else set()
        if fn in stack:
            return {}
        stack.add(fn)
        out: Dict[str, Tuple[str, int, str]] = {}
        m = fnode.module
        for acq in self.fn_acqs.get(fn, ()):
            if not acq.bounded and acq.lock not in out:
                out[acq.lock] = (m.rel_path, acq.node.lineno,
                                 fnode.qualname)
        for _call, target, _g in self.index.call_edges(m, fn):
            for lk, w in self.acquired_below(target, stack).items():
                out.setdefault(lk, w)
        stack.discard(fn)
        if _stack is None:
            self._below[fn] = out
        return out

    def blocking_below(self, fnode: FunctionNode,
                       _stack: Optional[Set[ast.AST]] = None
                       ) -> Optional[Tuple[str, int, str, str]]:
        """First unbounded-blocking witness reachable from ``fnode``:
        (rel_path, line, qualname, reason), or None. ``testing/`` modules
        are injection points, not blocking code, and are skipped."""
        fn = fnode.fn
        if fn in self._blocking:
            return self._blocking[fn]
        stack = _stack if _stack is not None else set()
        if fn in stack:
            return None
        stack.add(fn)
        out: Optional[Tuple[str, int, str, str]] = None
        m = fnode.module
        if "testing/" not in m.rel_path:
            for node in m.nodes_by_fn.get(fn, ()):
                if isinstance(node, ast.Call):
                    reason = self.blocking_reason(m, node, fn)
                    if reason is not None:
                        out = (m.rel_path, node.lineno, fnode.qualname,
                               reason)
                        break
            if out is None:
                for _call, target, _g in self.index.call_edges(m, fn):
                    below = self.blocking_below(target, stack)
                    if below is not None:
                        out = below
                        break
        stack.discard(fn)
        if _stack is None:
            self._blocking[fn] = out
        return out

    def blocking_reason(self, module, call: ast.Call,
                        fn: Optional[ast.AST]) -> Optional[str]:
        """Why this call can block unboundedly / sync the device, or
        None. Acquisitions of *resolvable* locks return None — nesting is
        TPU016's domain, and a Condition.wait on the held lock releases
        it rather than blocking under it."""
        f = call.func
        if isinstance(f, ast.Lambda):
            return None
        q = self.index.qualify(module, f)
        if q in _BLOCK_QUALS:
            return _BLOCK_QUALS[q]
        attr = f.attr if isinstance(f, ast.Attribute) else ""
        if attr in _SYNC_ATTRS:
            return f".{attr}() (device sync)"
        if module.scope.is_jit_call(call):
            return "a jit-compiled computation"
        cq = self.index.collective_name(module, call)
        if cq:
            return f"collective {cq}"
        target = self.index.resolve_call(module, call)
        if target is not None \
                and target.module.scope.fn_traced(target.fn):
            return f"traced function {target.qualname}"
        if attr in _IO_ATTRS:
            return f"blocking I/O .{attr}()"
        if target is None and attr in _ENGINE_ATTRS:
            return f".{attr}() (unbounded device work)"
        if isinstance(f, ast.Name) and fn is not None:
            args = getattr(fn, "args", None)
            if args is not None and _CB_PARAM.match(f.id):
                all_params = (list(args.args) + list(args.posonlyargs)
                              + list(args.kwonlyargs))
                if any(a.arg == f.id for a in all_params):
                    return f"opaque callback {f.id}()"
        if isinstance(f, ast.Attribute):
            recv = _UB._receiver(f)
            if attr == "join" and not call.args and not call.keywords:
                return f"unbounded {recv or 'thread'}.join()"
            if attr in ("acquire", "wait", "get") \
                    and not _UB._bounded(call):
                if self.resolve_lock_expr(module, f.value, fn) is not None:
                    return None       # known lock: TPU016/TPU019 territory
                if attr == "acquire" and _UB._LOCKISH.search(recv):
                    return f"unbounded {recv}.acquire()"
                if attr == "wait" and _UB._EVENTISH.search(recv):
                    return f"unbounded {recv}.wait()"
                if attr == "get" and _UB._QUEUEISH.search(recv):
                    return f"unbounded {recv}.get()"
        return None

    # --------------------------------------------------------- lock order

    def order_edges(self) -> Dict[Tuple[str, str], tuple]:
        """(outer lock, inner lock) -> (module, node, qualname, detail):
        somewhere in the project the inner lock is acquired — directly or
        through calls — while the outer is held. First witness wins."""
        if self._edges is not None:
            return self._edges
        edges: Dict[Tuple[str, str], tuple] = {}
        for m in self.index.modules:
            for fn in m.nodes_by_fn:
                if fn is None:
                    continue
                acqs = [a for a in self.fn_acqs.get(fn, ())
                        if not a.bounded]
                if not acqs:
                    continue
                qual = m.enclosing_qualname(fn)
                for a in acqs:
                    for b in acqs:
                        if b.lock != a.lock \
                                and self.covered(m, a, b.node):
                            edges.setdefault(
                                (a.lock, b.lock),
                                (m, a.node, qual,
                                 f"{self.short(b.lock)} acquired at "
                                 f"{m.rel_path}:{b.node.lineno}"))
                    for call, target, _g in self.index.call_edges(m, fn):
                        if not self.covered(m, a, call):
                            continue
                        for lk, (rel, ln, tq) in \
                                self.acquired_below(target).items():
                            if lk == a.lock:
                                continue
                            edges.setdefault(
                                (a.lock, lk),
                                (m, call, qual,
                                 f"via {target.qualname}(): "
                                 f"{self.short(lk)} acquired at "
                                 f"{rel}:{ln} in {tq}"))
        self._edges = edges
        return edges

    def inversions(self) -> List[Tuple[Tuple[str, str], tuple, tuple]]:
        """[(ordered pair, witness for that order, witness for the
        opposite order)] — each inversion reported once, anchored on the
        lexicographically-first direction's witness."""
        edges = self.order_edges()
        out = []
        for (a, b), w in sorted(edges.items()):
            if a < b and (b, a) in edges:
                out.append(((a, b), w, edges[(b, a)]))
        return out

    # --------------------------------------------------------- held context

    def context_held(self, fn: ast.AST) -> FrozenSet[str]:
        """Locks held at EVERY call site of ``fn`` (intersection-meet
        fixpoint; thread entries and uncalled functions hold nothing)."""
        if self._held is None:
            self._compute_context_held()
        held = self._held.get(fn)
        return held if held is not None else frozenset()

    def _compute_context_held(self) -> None:
        sites: Dict[ast.AST, List[tuple]] = {}
        for m in self.index.modules:
            for fn in m.nodes_by_fn:
                if fn is None:
                    continue
                for call, target, _g in self.index.call_edges(m, fn):
                    sites.setdefault(target.fn, []).append((m, fn, call))
        held: Dict[ast.AST, Optional[FrozenSet[str]]] = {}
        for m in self.index.modules:
            for fn in m.nodes_by_fn:
                if fn is None:
                    continue
                if fn in self.entries or fn not in sites:
                    held[fn] = frozenset()
                else:
                    held[fn] = None                     # TOP (no info yet)
        for _pass in range(20):
            changed = False
            for fn, slist in sites.items():
                if fn in self.entries:
                    continue            # entry: runs with nothing held
                acc: Optional[Set[str]] = None
                for m, cfn, call in slist:
                    ctx = held.get(cfn)
                    if ctx is None:
                        continue                        # optimistic: TOP
                    site = self.locks_covering(m, cfn, call,
                                               include_bounded=True) | ctx
                    acc = set(site) if acc is None else (acc & site)
                if acc is None:
                    continue
                new = frozenset(acc)
                if held.get(fn) != new:
                    held[fn] = new
                    changed = True
            if not changed:
                break
        self._held = held

    # ------------------------------------------------------------- display

    def short(self, lock_id: str) -> str:
        return lock_id[len("deepspeed_tpu."):] \
            if lock_id.startswith("deepspeed_tpu.") else lock_id


def get_model(index: ProjectIndex) -> LockModel:
    """The lint run's LockModel, built once and cached on the index."""
    model = getattr(index, "_gl_lock_model", None)
    if model is None:
        model = LockModel(index)
        index._gl_lock_model = model
    return model
