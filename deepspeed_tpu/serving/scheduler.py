"""Continuous-batching request scheduler: admission control + FIFO queue.

The serving loop (serving/engine.py) is a fixed-shape decode step over
``max_batch`` lanes; this module decides WHICH requests occupy those
lanes. Design contract:

* **Admission control by block budget.** A request is admitted only when
  the pool can cover its whole lifetime — ``ceil((prompt + max_new - 1)
  / block_size)`` blocks, minus whatever a prefix-cache hit contributes.
  Admitting on the full lifetime (not just the prompt) means an admitted
  sequence can NEVER hit the pool mid-decode: exhaustion is a
  queue-time, not a crash-time, condition.
* **Strict FIFO.** If the head of the queue does not fit, nothing behind
  it is admitted either — a stream of small requests cannot starve a big
  one (fairness under a full pool is a pinned test).
* **In-flight batching.** ``next_admission`` is consulted every loop
  iteration, so new prefills enter as soon as finishing sequences return
  their blocks — no batch drain barrier.

Failpoints (testing/chaos.py): ``serve.enqueue`` fires in :meth:`submit`
(a rejected/exploding enqueue must surface to the caller, not wedge the
loop); ``serve.oom`` fires inside ``BlockPool.alloc`` (the engine treats
it exactly like a genuinely full pool: the request stays queued).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..testing import chaos
from ..utils.logging import logger
from .kv_cache import BlockPool, PrefixCache

#: request lifecycle states
QUEUED, PREFILL, RUNNING, FINISHED, FAILED = (
    "QUEUED", "PREFILL", "RUNNING", "FINISHED", "FAILED")

_rid = itertools.count()


@dataclass
class Request:
    """One generation request riding the serving loop."""
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token_id: Optional[int] = None
    on_finish: Optional[Callable[["Request"], None]] = None
    rid: int = field(default_factory=lambda: next(_rid))
    # -- filled by the engine -------------------------------------------------
    state: str = QUEUED
    output_tokens: List[int] = field(default_factory=list)
    prefix_hit_tokens: int = 0
    arrival_ts: float = field(default_factory=time.monotonic)
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    error: Optional[str] = None

    @property
    def tokens(self) -> List[int]:
        return list(self.prompt) + list(self.output_tokens)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, FAILED)

    def _finish(self, state: str = FINISHED,
                error: Optional[str] = None) -> None:
        self.state = state
        self.error = error
        self.finish_ts = time.monotonic()
        if self.on_finish is not None:
            try:
                self.on_finish(self)
            except Exception:           # callbacks must not kill the loop
                logger.exception("serving: on_finish callback for request "
                                 "%d raised", self.rid)


class Scheduler:
    """FIFO queue + block-budget admission over a shared :class:`BlockPool`.

    Thread-safe on the queue: ``submit`` may be called from any thread
    (the Poisson load generator, an RPC handler); admission and
    completion run on the serving loop's thread.
    """

    def __init__(self, pool: BlockPool, max_queue: int = 4096,
                 max_model_len: Optional[int] = None,
                 prefix_cache: Optional[PrefixCache] = None):
        self.pool = pool
        self.prefix_cache = prefix_cache
        self.max_queue = int(max_queue)
        self.max_model_len = max_model_len
        self._queue: deque = deque()
        self._lock = threading.Lock()

    # ------------------------------------------------------------ queue side

    def submit(self, req: Request) -> Request:
        """Enqueue; raises on a full queue or an over-long request (the
        caller must know synchronously — a silently dropped request is a
        hung client)."""
        chaos.failpoint("serve.enqueue")
        total = len(req.prompt) + req.max_new_tokens
        if not req.prompt:
            raise ValueError("empty prompt")
        if self.max_model_len is not None and total > self.max_model_len:
            raise ValueError(
                f"request {req.rid}: prompt + max_new_tokens = {total} "
                f"exceeds max_model_len {self.max_model_len}")
        # a lifetime budget beyond the WHOLE pool could never be admitted:
        # under strict FIFO it would wedge the queue forever (and no
        # watchdog would fire — the loop keeps iterating). Reject now.
        allocatable = self.pool.num_blocks - 1
        if self.blocks_needed(req) > allocatable:
            raise ValueError(
                f"request {req.rid}: needs {self.blocks_needed(req)} KV "
                f"blocks, pool has {allocatable} total — raise "
                "serving.pool_blocks or shrink the request")
        with self._lock:
            if len(self._queue) >= self.max_queue:
                raise RuntimeError(
                    f"serving queue full ({self.max_queue}); apply "
                    "backpressure upstream")
            self._queue.append(req)
        return req

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def pending(self) -> int:
        return len(self)

    # -------------------------------------------------------- admission side

    def blocks_needed(self, req: Request, prefix_tokens: int = 0) -> int:
        """Lifetime block budget: the cache holds prompt + max_new - 1
        tokens (the final sampled token is never written back), minus the
        full blocks a prefix hit already provides."""
        life = len(req.prompt) + max(req.max_new_tokens - 1, 0)
        return self.pool.blocks_for_tokens(life - prefix_tokens)

    def next_admission(self) -> Optional[Request]:
        """Pop the head iff its block budget fits (strict FIFO: a head
        that does not fit blocks everything behind it). Tries prefix-cache
        eviction before giving up — cached-but-unused blocks must never
        starve admissions."""
        with self._lock:
            if not self._queue:
                return None
            head = self._queue[0]
            hit_tokens, hit_key = ((0, None) if self.prefix_cache is None
                                   else self.prefix_cache.peek(head.prompt))
            # budget NET of the prefix hit, and the make-room eviction
            # protects the hit's entry — the head's own reusable prefix
            # must never be the victim of admitting the head
            need = self.blocks_needed(head, prefix_tokens=hit_tokens)
            if need > self.pool.free_count and self.prefix_cache is not None:
                self.prefix_cache.evict(need, protect=hit_key)
            if need > self.pool.free_count:
                return None
            self._queue.popleft()
            return head

    def requeue_front(self, req: Request) -> None:
        """Put an admission back at the HEAD (transient allocation failure
        — chaos 'serve.oom' or a racing allocation): FIFO order is
        preserved and the request is retried next iteration."""
        with self._lock:
            self._queue.appendleft(req)
