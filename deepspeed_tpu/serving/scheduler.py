"""Continuous-batching request scheduler: admission + tiered FIFO queue.

The serving loop (serving/engine.py) is a fixed-shape decode step over
``max_batch`` lanes; this module decides WHICH requests occupy those
lanes. Design contract:

* **Admission control by block budget.** A request is admitted only when
  the pool can cover its whole lifetime — ``ceil((prompt + max_new - 1)
  / block_size)`` blocks, minus whatever a prefix-cache hit contributes.
  Admitting on the full lifetime (not just the prompt) means an admitted
  sequence can NEVER hit the pool mid-decode: exhaustion is a
  queue-time, not a crash-time, condition.
* **Strict FIFO.** If the head of the queue does not fit, nothing behind
  it is admitted either — a stream of small requests cannot starve a big
  one (fairness under a full pool is a pinned test).
* **Per-request deadlines (round 11).** Strict FIFO has an unbounded-wait
  edge: a too-big head makes everything behind it wait for as long as the
  head waits. A request submitted with ``deadline_s`` (a TTL relative to
  arrival) is SHED with a ``TIMEOUT`` result once the deadline passes and
  it is still queued — checked at every admission pass, anywhere in the
  queue, so backpressure degrades into bounded-latency load shedding
  instead of silent starvation. A request already admitted (PREFILL /
  RUNNING) is never shed: its blocks are paid for and killing it would
  waste the work — deadlines bound *queue wait*, not generation.
* **In-flight batching.** ``next_admission`` is consulted every loop
  iteration, so new prefills enter as soon as finishing sequences return
  their blocks — no batch drain barrier.
* **Priority tiers (round 19).** ``submit(priority=)`` picks one of
  latency / standard / batch. :class:`TieredQueue` serves the highest
  tier first, strict FIFO *within* a tier, with one starvation bound: a
  tier head that has waited longer than ``aging_s`` is served as if it
  were latency-tier (the aging floor — batch work is deferrable, not
  droppable). All-default traffic lives in one tier and degenerates to
  exactly the old FIFO, so every strict-FIFO pin still holds.
* **Overload ladder (round 19).** Backpressure escalates, never hangs and
  never silently drops: (1) expired queued requests are shed with
  TIMEOUT (round 11); (2) past ``batch_highwater`` of ``max_queue`` new
  batch-tier submissions get a machine-readable
  :class:`AdmissionRejected`; (3) at a hard-full queue a higher-tier
  arrival SHEDs the youngest queued request of the lowest tier below it
  (victim concludes ``SHED``, callback fires) — and when no lower-tier
  victim exists the arrival itself is rejected machine-readably.

Failpoints (testing/chaos.py): ``serve.enqueue`` fires in :meth:`submit`
(a rejected/exploding enqueue must surface to the caller, not wedge the
loop); ``serve.oom`` fires inside ``BlockPool.alloc`` (the engine treats
it exactly like a genuinely full pool: the request stays queued).
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional

from ..testing import chaos
from ..utils.logging import logger
from .kv_cache import BlockPool, PrefixCache

#: request lifecycle states. TIMEOUT (round 11) is a terminal shed: the
#: request's deadline passed while it was still QUEUED — never applied to
#: an admitted request. HANDOFF (round 12) is the disaggregated-serving
#: window between a finished prefill and its installation into a decode
#: lane: the request's blocks sit in the block-handoff queue
#: (serving/disagg.py) with its sampler state (first token, table).
#: SHED (round 19) is the overload ladder's terminal: a queued request
#: evicted to admit a higher-tier arrival at a hard-full queue — like
#: TIMEOUT it only ever applies to a QUEUED request and its callback
#: fires with a machine-readable error.
QUEUED, PREFILL, RUNNING, FINISHED, FAILED, TIMEOUT, HANDOFF, SHED = (
    "QUEUED", "PREFILL", "RUNNING", "FINISHED", "FAILED", "TIMEOUT",
    "HANDOFF", "SHED")

#: priority tiers (round 19), highest first. Rank 0 dispatches first.
LATENCY, STANDARD, BATCH = "latency", "standard", "batch"
PRIORITY_TIERS = (LATENCY, STANDARD, BATCH)
TIER_RANK = {LATENCY: 0, STANDARD: 1, BATCH: 2}

_rid = itertools.count()


class AdmissionRejected(RuntimeError):
    """Machine-readable admission rejection (round 19 overload ladder).

    Subclasses RuntimeError so callers catching the round-8 full-queue
    error keep working; ``info`` carries the structured verdict a client
    can branch on (retry-after vs downgrade-tier vs give-up) and the
    message embeds it as JSON — never a hang, never a silent drop."""

    def __init__(self, reason: str, tier: str, queue: int, max_queue: int):
        self.info = {"error": "admission_rejected", "reason": reason,
                     "tier": tier, "queue": queue, "max_queue": max_queue}
        super().__init__(
            f"serving queue full ({queue}/{max_queue}): "
            + json.dumps(self.info, sort_keys=True))


def check_admissible(prompt_tokens: int, max_new_tokens: int,
                     block_size: int, num_blocks: int,
                     max_model_len: Optional[int],
                     label: str = "request") -> None:
    """THE admissibility predicate, shared by engine-level
    ``Scheduler.submit`` and fleet-level ``ServingFleet.submit`` (every
    replica has the same pool geometry): empty prompts, requests beyond
    ``max_model_len``, and lifetime block budgets no pool of
    ``num_blocks`` (one reserved null block) could EVER cover are
    rejected synchronously — under strict FIFO an inadmissible head
    would wedge the queue forever while the loop keeps heartbeating."""
    if prompt_tokens <= 0:
        raise ValueError("empty prompt")
    total = prompt_tokens + max_new_tokens
    if max_model_len is not None and total > max_model_len:
        raise ValueError(
            f"{label}: prompt + max_new_tokens = {total} "
            f"exceeds max_model_len {max_model_len}")
    life = prompt_tokens + max(max_new_tokens - 1, 0)
    need = -(-max(life, 0) // block_size)       # BlockPool.blocks_for_tokens
    allocatable = num_blocks - 1                # null block reserved
    if need > allocatable:
        raise ValueError(
            f"{label}: needs {need} KV blocks, pool has {allocatable} "
            "total — raise serving.pool_blocks or shrink the request")


@dataclass
class Request:
    """One generation request riding the serving loop."""
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token_id: Optional[int] = None
    on_finish: Optional[Callable[["Request"], None]] = None
    #: absolute monotonic deadline; a still-QUEUED request past it is shed
    #: with TIMEOUT at the next admission pass (None = wait forever)
    deadline_ts: Optional[float] = None
    #: priority tier (round 19): latency | standard | batch
    priority: str = STANDARD
    rid: int = field(default_factory=lambda: next(_rid))
    # -- filled by the engine -------------------------------------------------
    state: str = QUEUED
    output_tokens: List[int] = field(default_factory=list)
    prefix_hit_tokens: int = 0
    #: prompt tokens whose K/V reached the pool (round 12): chunked
    #: prefill advances it per chunk, so a requeue after a mid-prefill
    #: replica death carries how far the dead leg got (death ledger /
    #: observability; the retry recomputes from its own prefix hits)
    prefill_progress: int = 0
    arrival_ts: float = field(default_factory=time.monotonic)
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    error: Optional[str] = None

    @property
    def tokens(self) -> List[int]:
        return list(self.prompt) + list(self.output_tokens)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, FAILED, TIMEOUT, SHED)

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline_ts is None:
            return False
        return (time.monotonic() if now is None else now) > self.deadline_ts

    def _finish(self, state: str = FINISHED,
                error: Optional[str] = None) -> None:
        self.state = state
        self.error = error
        self.finish_ts = time.monotonic()
        if self.on_finish is not None:
            try:
                self.on_finish(self)
            except Exception:           # callbacks must not kill the loop
                logger.exception("serving: on_finish callback for request "
                                 "%d raised", self.rid)


class TieredQueue:
    """Priority-tiered FIFO queue (round 19): one deque per tier, highest
    tier dispatched first, strict FIFO within a tier, and an aging floor
    — a tier head that has waited longer than ``aging_s`` seconds is
    served as if it were top-tier, so batch work is deferred, never
    starved. NOT internally locked: every caller (engine Scheduler,
    ServingFleet, ProcessFleet) already serializes queue access under its
    own lock, and a second lock here would only add ordering hazards
    (graftlint TPU017). With all traffic in one tier this is exactly a
    deque — the strict-FIFO contract the round-8/11 tests pin."""

    def __init__(self, aging_s: float = 30.0):
        self.aging_s = float(aging_s)
        self._tiers: Dict[str, deque] = {t: deque() for t in PRIORITY_TIERS}

    @staticmethod
    def _tier(req) -> str:
        t = getattr(req, "priority", STANDARD)
        return t if t in TIER_RANK else STANDARD

    def append(self, req) -> None:
        self._tiers[self._tier(req)].append(req)

    def appendleft(self, req) -> None:
        """Front of the request's OWN tier (requeue-after-death /
        preemption): it resumes ahead of its peers, not ahead of higher
        tiers — preempting batch work must not promote it."""
        self._tiers[self._tier(req)].appendleft(req)

    def __len__(self) -> int:
        return sum(len(q) for q in self._tiers.values())

    def __iter__(self) -> Iterator:
        for t in PRIORITY_TIERS:
            yield from self._tiers[t]

    def peeknext(self, now: Optional[float] = None):
        """The ONE logical head: among the three tier heads, the best
        (effective-rank, arrival) pair. Effective rank is the tier rank
        unless the head has aged past ``aging_s`` — then it competes at
        rank 0. Strict head-blocking admission applies to THIS head only
        (the round-8 fairness pin, per tier)."""
        if now is None:
            now = time.monotonic()
        best_key, best = None, None
        for tier in PRIORITY_TIERS:
            q = self._tiers[tier]
            if not q:
                continue
            head = q[0]
            rank = TIER_RANK[tier]
            if rank and self.aging_s > 0 and \
                    (now - head.arrival_ts) > self.aging_s:
                rank = 0
            key = (rank, head.arrival_ts, TIER_RANK[tier])
            if best_key is None or key < best_key:
                best_key, best = key, head
        return best

    def popnext(self, now: Optional[float] = None):
        head = self.peeknext(now)
        if head is not None:
            self._tiers[self._tier(head)].popleft()
        return head

    def remove(self, req) -> bool:
        """Remove a specific request (admission pop after a peek, or a
        shed): True iff it was queued."""
        q = self._tiers[self._tier(req)]
        try:
            q.remove(req)
            return True
        except ValueError:
            return False

    def remove_expired(self, now: float) -> List:
        """Extract every queued request past its deadline (the caller
        concludes them with TIMEOUT outside its lock)."""
        expired: List = []
        for tier, q in self._tiers.items():
            if any(r.expired(now) for r in q):
                expired.extend(r for r in q if r.expired(now))
                self._tiers[tier] = deque(r for r in q if not r.expired(now))
        return expired

    def shed_victim(self, arriving_rank: int):
        """The overload ladder's hard-full rung: extract the YOUNGEST
        queued request of the LOWEST tier strictly below ``arriving_rank``
        (None when no lower tier has anything — the arrival itself must
        then be rejected). Youngest-first minimizes wasted queue wait."""
        for tier in reversed(PRIORITY_TIERS):
            if TIER_RANK[tier] <= arriving_rank:
                return None
            q = self._tiers[tier]
            if q:
                return q.pop()
        return None

    def pressured(self, window_s: float, now: float) -> int:
        """Deadline pressure: queued requests whose remaining TTL is
        inside ``window_s`` (the autoscaler's second trigger). 0 when the
        window is off."""
        if window_s <= 0:
            return 0
        return sum(1 for q in self._tiers.values() for r in q
                   if r.deadline_ts is not None
                   and (r.deadline_ts - now) < window_s)


def admit_or_shed(tq: TieredQueue, req, max_queue: int,
                  batch_highwater: float = 1.0):
    """THE shared admission ladder (engine Scheduler + both fleet
    placements; caller holds its own queue lock). Appends ``req`` and
    returns the shed victim to conclude (outside the lock), or raises
    :class:`AdmissionRejected` — never a hang, never a silent drop."""
    tier = TieredQueue._tier(req)
    depth = len(tq)
    if depth >= max_queue:
        victim = tq.shed_victim(TIER_RANK[tier])
        if victim is None:
            raise AdmissionRejected("queue_full", tier, depth, max_queue)
        tq.append(req)
        return victim
    if tier == BATCH and depth >= batch_highwater * max_queue:
        raise AdmissionRejected("batch_highwater", tier, depth, max_queue)
    tq.append(req)
    return None


class Scheduler:
    """FIFO queue + block-budget admission over a shared :class:`BlockPool`.

    Thread-safe on the queue: ``submit`` may be called from any thread
    (the Poisson load generator, an RPC handler); admission and
    completion run on the serving loop's thread.
    """

    def __init__(self, pool: BlockPool, max_queue: int = 4096,
                 max_model_len: Optional[int] = None,
                 prefix_cache: Optional[PrefixCache] = None,
                 aging_s: float = 30.0, batch_highwater: float = 1.0):
        self.pool = pool
        self.prefix_cache = prefix_cache
        self.max_queue = int(max_queue)
        self.max_model_len = max_model_len
        self._queue = TieredQueue(aging_s=aging_s)
        self.batch_highwater = float(batch_highwater)
        self._lock = threading.Lock()
        self.timed_out = 0           # requests shed past their deadline
        self.shed = 0                # requests shed by the overload ladder

    # ------------------------------------------------------------ queue side

    def submit(self, req: Request) -> Request:
        """Enqueue; raises on a full queue or an over-long request (the
        caller must know synchronously — a silently dropped request is a
        hung client). At a hard-full queue the round-19 ladder applies:
        a higher-tier arrival sheds the youngest lowest-tier queued
        request instead of being rejected (see :func:`admit_or_shed`)."""
        chaos.failpoint("serve.enqueue")
        check_admissible(len(req.prompt), req.max_new_tokens,
                         self.pool.block_size, self.pool.num_blocks,
                         self.max_model_len, label=f"request {req.rid}")
        with self._lock:
            victim = admit_or_shed(self._queue, req, self.max_queue,
                                   self.batch_highwater)
            if victim is not None:
                self.shed += 1
        if victim is not None:
            victim._finish(SHED, error=json.dumps(
                {"error": "shed", "reason": "displaced_by_tier",
                 "tier": TieredQueue._tier(victim)}, sort_keys=True))
        return req

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def pending(self) -> int:
        return len(self)

    # -------------------------------------------------------- admission side

    def blocks_needed(self, req: Request, prefix_tokens: int = 0) -> int:
        """Lifetime block budget: the cache holds prompt + max_new - 1
        tokens (the final sampled token is never written back), minus the
        full blocks a prefix hit already provides."""
        life = len(req.prompt) + max(req.max_new_tokens - 1, 0)
        return self.pool.blocks_for_tokens(life - prefix_tokens)

    def shed_expired(self) -> List[Request]:
        """Remove every still-queued request whose deadline has passed and
        conclude each with a TIMEOUT result (callback fires — the caller
        learns synchronously that the request was shed, not silently
        dropped). Runs at every admission pass; callbacks fire OUTSIDE the
        queue lock so an on_finish that resubmits cannot deadlock."""
        now = time.monotonic()
        with self._lock:
            expired = self._queue.remove_expired(now)
            self.timed_out += len(expired)
        for req in expired:
            logger.warning("serving: request %d shed past its deadline "
                           "after %.2fs queued", req.rid,
                           now - req.arrival_ts)
            req._finish(TIMEOUT, error="deadline exceeded while queued")
        return expired

    def next_admission(self) -> Optional[Request]:
        """Pop the head iff its block budget fits (strict FIFO: a head
        that does not fit blocks everything behind it). The caller runs
        :meth:`shed_expired` once per admission PASS (the engine's
        ``_admit`` does, even with every lane busy) — not per pop, which
        would rescan the whole queue for each admitted request. Tries
        prefix-cache eviction before giving up — cached-but-unused
        blocks must never starve admissions."""
        with self._lock:
            head = self._queue.peeknext()
            if head is None:
                return None
            hit_tokens, hit_key = ((0, None) if self.prefix_cache is None
                                   else self.prefix_cache.peek(head.prompt))
            # budget NET of the prefix hit, and the make-room eviction
            # protects the hit's entry — the head's own reusable prefix
            # must never be the victim of admitting the head
            need = self.blocks_needed(head, prefix_tokens=hit_tokens)
            if need > self.pool.free_count and self.prefix_cache is not None:
                self.prefix_cache.evict(need, protect=hit_key)
            if need > self.pool.free_count:
                return None
            self._queue.remove(head)
            return head

    def withdraw(self, req: Request) -> bool:
        """Remove a still-queued request without concluding it (the
        process-fleet cancel path); True iff it was queued here."""
        with self._lock:
            return self._queue.remove(req)

    def requeue_front(self, req: Request) -> None:
        """Put an admission back at the HEAD (transient allocation failure
        — chaos 'serve.oom' or a racing allocation): FIFO order is
        preserved and the request is retried next iteration."""
        with self._lock:
            self._queue.appendleft(req)
