"""Continuous-batching request scheduler: admission control + FIFO queue.

The serving loop (serving/engine.py) is a fixed-shape decode step over
``max_batch`` lanes; this module decides WHICH requests occupy those
lanes. Design contract:

* **Admission control by block budget.** A request is admitted only when
  the pool can cover its whole lifetime — ``ceil((prompt + max_new - 1)
  / block_size)`` blocks, minus whatever a prefix-cache hit contributes.
  Admitting on the full lifetime (not just the prompt) means an admitted
  sequence can NEVER hit the pool mid-decode: exhaustion is a
  queue-time, not a crash-time, condition.
* **Strict FIFO.** If the head of the queue does not fit, nothing behind
  it is admitted either — a stream of small requests cannot starve a big
  one (fairness under a full pool is a pinned test).
* **Per-request deadlines (round 11).** Strict FIFO has an unbounded-wait
  edge: a too-big head makes everything behind it wait for as long as the
  head waits. A request submitted with ``deadline_s`` (a TTL relative to
  arrival) is SHED with a ``TIMEOUT`` result once the deadline passes and
  it is still queued — checked at every admission pass, anywhere in the
  queue, so backpressure degrades into bounded-latency load shedding
  instead of silent starvation. A request already admitted (PREFILL /
  RUNNING) is never shed: its blocks are paid for and killing it would
  waste the work — deadlines bound *queue wait*, not generation.
* **In-flight batching.** ``next_admission`` is consulted every loop
  iteration, so new prefills enter as soon as finishing sequences return
  their blocks — no batch drain barrier.

Failpoints (testing/chaos.py): ``serve.enqueue`` fires in :meth:`submit`
(a rejected/exploding enqueue must surface to the caller, not wedge the
loop); ``serve.oom`` fires inside ``BlockPool.alloc`` (the engine treats
it exactly like a genuinely full pool: the request stays queued).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..testing import chaos
from ..utils.logging import logger
from .kv_cache import BlockPool, PrefixCache

#: request lifecycle states. TIMEOUT (round 11) is a terminal shed: the
#: request's deadline passed while it was still QUEUED — never applied to
#: an admitted request. HANDOFF (round 12) is the disaggregated-serving
#: window between a finished prefill and its installation into a decode
#: lane: the request's blocks sit in the block-handoff queue
#: (serving/disagg.py) with its sampler state (first token, table).
QUEUED, PREFILL, RUNNING, FINISHED, FAILED, TIMEOUT, HANDOFF = (
    "QUEUED", "PREFILL", "RUNNING", "FINISHED", "FAILED", "TIMEOUT",
    "HANDOFF")

_rid = itertools.count()


def check_admissible(prompt_tokens: int, max_new_tokens: int,
                     block_size: int, num_blocks: int,
                     max_model_len: Optional[int],
                     label: str = "request") -> None:
    """THE admissibility predicate, shared by engine-level
    ``Scheduler.submit`` and fleet-level ``ServingFleet.submit`` (every
    replica has the same pool geometry): empty prompts, requests beyond
    ``max_model_len``, and lifetime block budgets no pool of
    ``num_blocks`` (one reserved null block) could EVER cover are
    rejected synchronously — under strict FIFO an inadmissible head
    would wedge the queue forever while the loop keeps heartbeating."""
    if prompt_tokens <= 0:
        raise ValueError("empty prompt")
    total = prompt_tokens + max_new_tokens
    if max_model_len is not None and total > max_model_len:
        raise ValueError(
            f"{label}: prompt + max_new_tokens = {total} "
            f"exceeds max_model_len {max_model_len}")
    life = prompt_tokens + max(max_new_tokens - 1, 0)
    need = -(-max(life, 0) // block_size)       # BlockPool.blocks_for_tokens
    allocatable = num_blocks - 1                # null block reserved
    if need > allocatable:
        raise ValueError(
            f"{label}: needs {need} KV blocks, pool has {allocatable} "
            "total — raise serving.pool_blocks or shrink the request")


@dataclass
class Request:
    """One generation request riding the serving loop."""
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    eos_token_id: Optional[int] = None
    on_finish: Optional[Callable[["Request"], None]] = None
    #: absolute monotonic deadline; a still-QUEUED request past it is shed
    #: with TIMEOUT at the next admission pass (None = wait forever)
    deadline_ts: Optional[float] = None
    rid: int = field(default_factory=lambda: next(_rid))
    # -- filled by the engine -------------------------------------------------
    state: str = QUEUED
    output_tokens: List[int] = field(default_factory=list)
    prefix_hit_tokens: int = 0
    #: prompt tokens whose K/V reached the pool (round 12): chunked
    #: prefill advances it per chunk, so a requeue after a mid-prefill
    #: replica death carries how far the dead leg got (death ledger /
    #: observability; the retry recomputes from its own prefix hits)
    prefill_progress: int = 0
    arrival_ts: float = field(default_factory=time.monotonic)
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    error: Optional[str] = None

    @property
    def tokens(self) -> List[int]:
        return list(self.prompt) + list(self.output_tokens)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, FAILED, TIMEOUT)

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline_ts is None:
            return False
        return (time.monotonic() if now is None else now) > self.deadline_ts

    def _finish(self, state: str = FINISHED,
                error: Optional[str] = None) -> None:
        self.state = state
        self.error = error
        self.finish_ts = time.monotonic()
        if self.on_finish is not None:
            try:
                self.on_finish(self)
            except Exception:           # callbacks must not kill the loop
                logger.exception("serving: on_finish callback for request "
                                 "%d raised", self.rid)


class Scheduler:
    """FIFO queue + block-budget admission over a shared :class:`BlockPool`.

    Thread-safe on the queue: ``submit`` may be called from any thread
    (the Poisson load generator, an RPC handler); admission and
    completion run on the serving loop's thread.
    """

    def __init__(self, pool: BlockPool, max_queue: int = 4096,
                 max_model_len: Optional[int] = None,
                 prefix_cache: Optional[PrefixCache] = None):
        self.pool = pool
        self.prefix_cache = prefix_cache
        self.max_queue = int(max_queue)
        self.max_model_len = max_model_len
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self.timed_out = 0           # requests shed past their deadline

    # ------------------------------------------------------------ queue side

    def submit(self, req: Request) -> Request:
        """Enqueue; raises on a full queue or an over-long request (the
        caller must know synchronously — a silently dropped request is a
        hung client)."""
        chaos.failpoint("serve.enqueue")
        check_admissible(len(req.prompt), req.max_new_tokens,
                         self.pool.block_size, self.pool.num_blocks,
                         self.max_model_len, label=f"request {req.rid}")
        with self._lock:
            if len(self._queue) >= self.max_queue:
                raise RuntimeError(
                    f"serving queue full ({self.max_queue}); apply "
                    "backpressure upstream")
            self._queue.append(req)
        return req

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def pending(self) -> int:
        return len(self)

    # -------------------------------------------------------- admission side

    def blocks_needed(self, req: Request, prefix_tokens: int = 0) -> int:
        """Lifetime block budget: the cache holds prompt + max_new - 1
        tokens (the final sampled token is never written back), minus the
        full blocks a prefix hit already provides."""
        life = len(req.prompt) + max(req.max_new_tokens - 1, 0)
        return self.pool.blocks_for_tokens(life - prefix_tokens)

    def shed_expired(self) -> List[Request]:
        """Remove every still-queued request whose deadline has passed and
        conclude each with a TIMEOUT result (callback fires — the caller
        learns synchronously that the request was shed, not silently
        dropped). Runs at every admission pass; callbacks fire OUTSIDE the
        queue lock so an on_finish that resubmits cannot deadlock."""
        now = time.monotonic()
        with self._lock:
            expired = [r for r in self._queue if r.expired(now)]
            if expired:
                self._queue = deque(r for r in self._queue
                                    if not r.expired(now))
                self.timed_out += len(expired)
        for req in expired:
            logger.warning("serving: request %d shed past its deadline "
                           "after %.2fs queued", req.rid,
                           now - req.arrival_ts)
            req._finish(TIMEOUT, error="deadline exceeded while queued")
        return expired

    def next_admission(self) -> Optional[Request]:
        """Pop the head iff its block budget fits (strict FIFO: a head
        that does not fit blocks everything behind it). The caller runs
        :meth:`shed_expired` once per admission PASS (the engine's
        ``_admit`` does, even with every lane busy) — not per pop, which
        would rescan the whole queue for each admitted request. Tries
        prefix-cache eviction before giving up — cached-but-unused
        blocks must never starve admissions."""
        with self._lock:
            if not self._queue:
                return None
            head = self._queue[0]
            hit_tokens, hit_key = ((0, None) if self.prefix_cache is None
                                   else self.prefix_cache.peek(head.prompt))
            # budget NET of the prefix hit, and the make-room eviction
            # protects the hit's entry — the head's own reusable prefix
            # must never be the victim of admitting the head
            need = self.blocks_needed(head, prefix_tokens=hit_tokens)
            if need > self.pool.free_count and self.prefix_cache is not None:
                self.prefix_cache.evict(need, protect=hit_key)
            if need > self.pool.free_count:
                return None
            self._queue.popleft()
            return head

    def requeue_front(self, req: Request) -> None:
        """Put an admission back at the HEAD (transient allocation failure
        — chaos 'serve.oom' or a racing allocation): FIFO order is
        preserved and the request is retried next iteration."""
        with self._lock:
            self._queue.appendleft(req)
