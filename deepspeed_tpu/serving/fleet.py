"""ServingFleet — supervised multi-replica serving with request requeue.

PR 8's serving engine is one replica with no failure story: a wedged or
killed loop takes every in-flight request with it. This module shrinks
the serving failure domain to one replica (ROADMAP item 1(c)): N
continuous-batching replica engines — weights SHARED in-process, KV
pools per-replica — pull work from ONE bounded admission queue, and a
:class:`FleetSupervisor` watches each replica's SERVE heartbeat records
(runtime/heartbeat.py) the way the PR-6 launcher stack watches training
ranks. Losing a replica costs one replica, not the fleet.

Failure semantics (the contract the chaos matrix in tests/test_fleet.py
pins):

* **Detection is the rc-117 silence contract, fleet-side.** Each replica
  worker stamps a SERVE record (with queue/active gauges) every loop
  iteration onto the fleet's heartbeat channel. A dead worker thread, or
  ``heartbeat_timeout`` seconds of record silence from a live one (the
  chaos ``serve.replica_hang`` shape — a loop wedged in a failpoint or a
  stuck device), declares the replica DOWN. The supervisor stamps a
  ``STALLED`` terminal record as evidence (``dstpu health`` on
  ``fleet.heartbeat_dir`` shows it) and records the replica's last
  heartbeat in ``fleet.deaths`` for attribution.
* **Teardown is replica-local.** Only the dead replica is torn down and
  (unless blacklisted) restarted with a fresh engine; surviving replicas
  keep their engines, pools and compiled programs — fleet throughput
  recovers without touching them (pinned by test).
* **Requeue is exactly-once.** A FleetRequest carries its
  tokens-emitted-so-far; a requeued request re-enters the queue with
  ``prompt + emitted`` as its prompt and only the REMAINING budget as
  ``max_new_tokens``, so the resumed replica replays the generated
  prefix through its prefix cache (prefill, full-block reuse when
  cached) and ``on_token`` callbacks never re-fire a token. Tokens a
  dying replica generated but never emitted are deliberately dropped —
  greedy decode regenerates them identically; emission, not generation,
  is the exactly-once boundary. The emission/discard race is closed
  under the per-replica lock: the supervisor marks a replica DOWN under
  the same lock the worker syncs tokens under, so a declared-dead
  replica can never emit concurrently with its requests being re-served.
* **Retry budget.** Every requeue costs one retry; past
  ``retry_budget`` the request concludes FAILED (callback fires, status
  observable) instead of bouncing between dying replicas forever. The
  ``serve.requeue`` failpoint fires inside the requeue itself: a crash
  THERE parks the request on an orphan list the supervisor retries next
  poll — a requeue failure defers a request, never loses it.
* **Blacklist / parole.** ``blacklist_after`` strikes quarantine a
  repeatedly-dying replica (no restart); when live replicas would drop
  below ``min_replicas`` the least-struck blacklisted replica is paroled
  back — the elastic agent's host machinery (PR 6), applied to serving.
* **Graceful degradation.** The fleet keeps serving at reduced capacity
  with replicas down; per-request deadlines (``deadline_s`` /
  ``fleet.default_deadline_s``) shed expired queued requests with a
  TIMEOUT status — bounded-latency load shedding, not silent starvation.

Chaos failpoints (testing/chaos.py): ``serve.replica_kill`` and
``serve.replica_hang`` fire at the top of each worker iteration, KEYED
by the replica index (``match=1`` takes out replica 1 only). In-process
replicas use ``raise`` / ``hang`` modes — ``kill`` mode would
``os._exit`` the whole process; it belongs to a future process-per-
replica deployment, where the same heartbeat channel does the same job.

Threading model: one worker thread per replica (dispatch and token
sync/stamp under the replica lock; the engine step runs OUTSIDE it so a
wedge inside XLA can never hold the lock the supervisor needs to fence
the replica), one supervisor thread (``poll_interval`` cadence;
``poll()`` is public for deterministic tests). ``submit()`` is
thread-safe from any thread. A hung worker is abandoned (daemon
threads; its per-replica pool leaks until process exit — the price of
in-process replicas, documented in docs/SERVING.md).
"""

from __future__ import annotations

import json
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..runtime import heartbeat as hb
from ..runtime.straggler import (STEP_MS_GAUGE, STRAGGLER_FLAG, StepClock,
                                 StragglerDetector)
from ..testing import chaos
from ..utils.logging import log_dist, logger
from .autoscale import (AUTOSCALER_RANK, SCALE_DOWN, SCALE_UP,
                        AutoscalePolicy, Observation, ScaleEvent)
from .engine import ServingEngine, resolve_kv_dtype
from .kv_cache import SharedPagedState
from .scheduler import (BATCH, FAILED, FINISHED, LATENCY, PRIORITY_TIERS,
                        QUEUED, RUNNING, SHED, STANDARD, TIER_RANK, TIMEOUT,
                        TieredQueue, admit_or_shed, check_admissible)

PyTree = Any

#: replica lifecycle states. RETIRED (round 19) concludes a scale-down
#: drain: the replica finished its lanes and left cleanly (EXIT stamp) —
#: unlike DOWN it is not a failure and earns no strike.
LIVE, DOWN, BLACKLISTED, RETIRED = "LIVE", "DOWN", "BLACKLISTED", "RETIRED"


@dataclass
class FleetRequest:
    """One generation request riding the fleet — survives replica death.

    ``output_tokens`` holds only EMITTED tokens (synced from a live
    replica under its lock, ``on_token`` fired per token); it is the
    exactly-once ledger a requeue resumes from. ``retries`` counts
    requeues; ``state`` ends FINISHED, FAILED (budget exhausted or a
    deterministic per-request failure) or TIMEOUT (deadline shed)."""
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    eos_token_id: Optional[int] = None
    deadline_ts: Optional[float] = None
    on_token: Optional[Callable[["FleetRequest", int], None]] = None
    on_finish: Optional[Callable[["FleetRequest"], None]] = None
    rid: int = 0
    #: priority tier (round 19): latency | standard | batch — dispatch
    #: order, the overload ladder's shed order, and preemption standing
    priority: str = STANDARD
    state: str = QUEUED
    output_tokens: List[int] = field(default_factory=list)
    retries: int = 0
    #: times a deadline-pressured latency request evicted this one's
    #: lane (requeued token-exact; does NOT charge the retry budget)
    preemptions: int = 0
    replica: Optional[int] = None      # current / last assignment
    #: disagg: prompt tokens the last (possibly dead) prefill leg got
    #: into the pool — requeue carries it for the death ledger
    prefill_progress: int = 0
    error: Optional[str] = None
    arrival_ts: float = field(default_factory=time.monotonic)
    finish_ts: Optional[float] = None
    _synced: int = 0                   # engine tokens consumed this leg
    _done_evt: threading.Event = field(default_factory=threading.Event)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, FAILED, TIMEOUT, SHED)

    @property
    def remaining(self) -> int:
        return max(self.max_new_tokens - len(self.output_tokens), 0)

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline_ts is None:
            return False
        return (time.monotonic() if now is None else now) > self.deadline_ts

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request concludes; True iff it did in time."""
        return self._done_evt.wait(timeout)

    def _finish(self, state: str, error: Optional[str] = None) -> bool:
        """First conclusion wins — exactly-once for on_finish too."""
        if self.done:
            return False
        self.state = state
        self.error = error
        self.finish_ts = time.monotonic()
        self._done_evt.set()
        if self.on_finish is not None:
            try:
                self.on_finish(self)
            except Exception:
                logger.exception("fleet: on_finish callback for request "
                                 "%d raised", self.rid)
        return True


class _Replica:
    """One replica slot: engine + worker thread + heartbeat writer.

    A restart builds a NEW _Replica for the same index (strikes carried
    over) — an abandoned hung worker holds the OLD object, whose DOWN
    state makes its loop exit if it ever wakes, and whose engine/pool it
    can scribble on harmlessly."""

    def __init__(self, idx: int, generation: int = 0, strikes: int = 0):
        self.idx = idx
        self.generation = generation
        self.strikes = strikes
        self.state = LIVE
        self.warming = False           # silence-exempt during warmup()
        #: scale-down in flight (round 19): dispatch skips a draining
        #: replica; its lanes finish, then the supervisor RETIREs it.
        #: State stays LIVE so death supervision still covers the drain
        #: window — a draining replica that dies requeues exactly-once.
        self.draining = False
        self.step_clock = StepClock()  # rolling per-iteration wall gauge
        self.engine: Optional[ServingEngine] = None
        self.thread: Optional[threading.Thread] = None
        self.writer: Optional[hb.HeartbeatWriter] = None
        self.lock = threading.Lock()   # worker step/sync vs supervisor down
        self.inflight: Dict[int, Any] = {}   # rid -> (FleetRequest, eng req)
        #: disagg decode role: a handoff item popped but not yet
        #: installed (the serve.handoff_drop death window) — its blocks
        #: ride the quarantine if the replica dies here
        self.holding: Optional[Any] = None
        self.error: Optional[str] = None
        self.started_ts = time.monotonic()


class ServingFleet:
    """N supervised replica serving loops behind one admission queue
    (module docstring has the failure semantics).

    ``serving`` is a ``ServingConfig`` (or dict); its ``fleet`` section
    (``FleetConfig``) sizes and tunes the fleet. ``params`` is shared by
    reference across replicas — per-replica state is the KV pool and the
    compiled programs.
    """

    def __init__(self, cfg, params: PyTree, serving=None,
                 heartbeat_dir: Optional[str] = None,
                 interpret: bool = False):
        from ..config.config import ServingConfig
        if serving is None:
            serving = ServingConfig()
        elif isinstance(serving, dict):
            serving = ServingConfig(**serving)
        self.cfg = cfg
        self.params = params
        self.scfg = serving
        self.fcfg = serving.fleet
        if str(self.fcfg.placement) == "process":
            raise ValueError(
                "serving.fleet.placement='process' builds a ProcessFleet "
                "(serving/procfleet.py) — construct one directly or go "
                "through serving.make_fleet(...)")
        self.interpret = interpret
        # disaggregated roles (round 12, serving/disagg.py): prefill
        # replicas fill paged blocks and hand them — zero-copy, over ONE
        # shared pool — to decode replicas through the bounded handoff
        self.n_prefill = int(self.fcfg.prefill_replicas)
        self.n_decode = int(self.fcfg.decode_replicas)
        if (self.n_prefill > 0) != (self.n_decode > 0):
            raise ValueError(
                "serving.fleet: prefill_replicas and decode_replicas "
                "must both be > 0 for disaggregated serving (got "
                f"{self.n_prefill}/{self.n_decode})")
        self.disagg = self.n_prefill > 0
        if self.disagg:
            from .disagg import BlockHandoff
            self.n_replicas = self.n_prefill + self.n_decode
            self._shared = SharedPagedState(
                cfg, serving, dtype=resolve_kv_dtype(serving))
            self._handoff = BlockHandoff(
                self._shared.pool, capacity=int(serving.handoff_queue),
                on_push=self._register_handoff)
            #: engine-request rid -> FleetRequest, recorded at dispatch so
            #: the push-time registration hook (which runs on the prefill
            #: worker thread, without its replica lock) needs no replica
            #: state — guarded by _qlock
            self._er2freq: Dict[int, FleetRequest] = {}
            #: engine-request rid -> (freq, er) for items in (or through)
            #: the handoff queue: registered atomically at push, consumed
            #: at decode dispatch / deadline shed — the exactly-once
            #: ledger across the role boundary (guarded by _qlock)
            self._handoff_inflight: Dict[int, tuple] = {}
            #: (replica, block-lists) of dead disagg replicas, released
            #: into the SHARED pool only once the replica thread is
            #: provably gone (its abandoned final step may still write
            #: through its old tables; releasing earlier could hand those
            #: blocks to a new owner mid-scribble)
            self._quarantine: List[tuple] = []
        else:
            self.n_replicas = max(1, int(self.fcfg.replicas))
            self._shared = None
            self._handoff = None
        # traffic-shaped autoscaling (round 19, serving/autoscale.py):
        # plain replicas only — disagg role counts are a placement
        # decision the queue-depth trigger cannot make
        self.autoscale: Optional[AutoscalePolicy] = None
        if self.fcfg.autoscale.enabled:
            if self.disagg:
                raise ValueError(
                    "serving.fleet.autoscale does not apply to "
                    "disaggregated fleets (role counts are a placement "
                    "decision) — unset prefill/decode_replicas")
            self.autoscale = AutoscalePolicy(self.fcfg.autoscale)
            self.n_replicas = min(max(self.n_replicas,
                                      self.autoscale.min_replicas),
                                  self.autoscale.max_replicas)
        self.heartbeat_dir = (heartbeat_dir or self.fcfg.heartbeat_dir
                              or tempfile.mkdtemp(prefix="dstpu-fleet-hb-"))
        self._queue = TieredQueue(                # guarded by _qlock
            aging_s=float(self.fcfg.priority_aging_s))
        self._qlock = threading.Lock()
        self._stats_lock = threading.Lock()      # counters bumped from N
        #                                          workers + supervisor
        self._orphans: List[FleetRequest] = []   # failed requeues, retried
        #: fenced-but-wedged replicas whose teardown awaits their lock
        self._pending_down: List[tuple] = []
        self._outstanding: Dict[int, FleetRequest] = {}
        self._rid = 0
        self._stop = threading.Event()
        self._started = False
        self._lock = threading.Lock()            # replica-list mutations
        self._replicas: List[_Replica] = [_Replica(i)
                                          for i in range(self.n_replicas)]
        self.supervisor = FleetSupervisor(self)
        #: death ledger: {replica, generation, reason, evidence (last
        #: heartbeat record), strikes, detected_ts, action,
        #: restarted_ts} — the attribution trail tests and the bench read
        self.deaths: List[dict] = []
        #: capacity ledger (round 19), the death-ledger idiom applied to
        #: scale events: every autoscaler verdict (up / up_failed / down)
        #: with its trigger, timestamps and queue/live evidence — what
        #: the bench records and the autoscaler heartbeat rank mirrors
        self.scale_events: List[ScaleEvent] = []
        self._as_writer: Optional[hb.HeartbeatWriter] = None
        self.stats: Dict[str, int] = {
            "submitted": 0, "completed": 0, "failed": 0, "timeout": 0,
            "requeues": 0, "deaths": 0, "restarts": 0, "paroles": 0,
            "blacklisted": 0, "tokens_emitted": 0, "shed": 0,
            "preempted": 0, "scale_ups": 0, "scale_downs": 0}
        # run-scoped channel: stale records from a previous fleet in a
        # reused dir must not trip silence at t=0 (PR-6 contract)
        hb.clear_channel(self.heartbeat_dir)
        log_dist(
            f"ServingFleet: {self.n_replicas} replicas, "
            f"retry_budget={self.fcfg.retry_budget}, "
            f"heartbeat_timeout={self.fcfg.heartbeat_timeout}s, "
            f"heartbeat_dir={self.heartbeat_dir}", ranks=[0])

    # -------------------------------------------------------------- lifecycle

    def start(self) -> "ServingFleet":
        if self._started:
            return self
        self._started = True
        for rep in self._replicas:
            self._launch(rep)
        if self.autoscale is not None:
            # the autoscaler's own heartbeat rank: scale events are
            # operator evidence in the SAME channel `dstpu health`
            # reads; refreshed every supervisor poll so the record
            # never reads as silent while the fleet is supervised
            self._as_writer = hb.HeartbeatWriter(
                self.heartbeat_dir, rank=AUTOSCALER_RANK,
                host="autoscaler",
                min_interval=float(self.fcfg.heartbeat_interval),
                refresh_interval=0.0)
            self._stamp_autoscaler(force=True)
        self.supervisor.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        """Stop the supervisor and workers; stamp EXIT terminal records
        for every live replica so a closed fleet reads as concluded, not
        silent. ``timeout`` bounds the WHOLE close (an abandoned hung
        worker must not stall shutdown). Outstanding requests are left
        un-concluded — drain first if they matter."""
        self.supervisor.stop()
        self._stop.set()
        deadline = time.monotonic() + timeout
        for rep in self._replicas:
            if rep.state != LIVE:
                continue                # hung/blacklisted: abandoned daemons
            t = rep.thread
            if t is not None and t.is_alive():
                t.join(max(0.0, deadline - time.monotonic()))
            if rep.writer is not None:
                rep.writer.stamp_terminal(hb.PHASE_EXIT, lock_timeout=1.0)
        if self._as_writer is not None:
            self._as_writer.stamp_terminal(hb.PHASE_EXIT, lock_timeout=1.0)
        if self.disagg:
            # items still crossing the role boundary return their blocks
            # (their requests are left un-concluded, same as the queue)
            self._handoff.drain_release()
            self._drain_quarantine()

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- submission

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               temperature: float = 0.0, eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               on_token=None, on_finish=None,
               priority: str = STANDARD) -> FleetRequest:
        """Enqueue onto the SHARED fleet queue (thread-safe, bounded —
        raises on a full queue or an inadmissible request, the caller
        must know synchronously). ``deadline_s`` defaults to
        ``fleet.default_deadline_s`` (0 = wait forever). ``priority``
        (round 19) picks the latency/standard/batch tier; at a hard-full
        queue a higher-tier arrival sheds the youngest lowest-tier
        queued request (victim concludes SHED, callback fires) and a
        rejection is always the machine-readable
        :class:`~.scheduler.AdmissionRejected` — never a hang, never a
        silent drop (docs/SERVING.md §Priority)."""
        chaos.failpoint("serve.enqueue")
        if priority not in TIER_RANK:
            raise ValueError(f"unknown priority tier {priority!r}; pick "
                             f"one of {PRIORITY_TIERS}")
        prompt = [int(t) for t in prompt]
        # eager admissibility — the SAME predicate every replica's
        # scheduler applies (shared pool geometry): a request no replica
        # could ever admit must be rejected now, not discovered
        # asynchronously at dispatch
        bs = int(self.scfg.block_size)
        check_admissible(
            len(prompt), int(max_new_tokens), bs,
            int(self.scfg.pool_blocks),
            min(int(self.scfg.max_blocks_per_seq) * bs,
                self.cfg.max_seq_len))
        if deadline_s is None and self.fcfg.default_deadline_s > 0:
            deadline_s = self.fcfg.default_deadline_s
        with self._qlock:
            self._rid += 1
            req = FleetRequest(
                prompt=prompt, max_new_tokens=int(max_new_tokens),
                temperature=float(temperature), eos_token_id=eos_token_id,
                on_token=on_token, on_finish=on_finish, rid=self._rid,
                priority=priority)
            if deadline_s is not None:
                req.deadline_ts = req.arrival_ts + float(deadline_s)
            # the round-19 overload ladder (scheduler.admit_or_shed):
            # raises AdmissionRejected before touching fleet state
            victim = admit_or_shed(self._queue, req,
                                   int(self.fcfg.max_queue),
                                   float(self.fcfg.batch_highwater))
            self._outstanding[req.rid] = req
        self._bump("submitted")
        if victim is not None:
            self._conclude(victim, SHED, json.dumps(
                {"error": "shed", "reason": "displaced_by_tier",
                 "tier": victim.priority}, sort_keys=True))
        return req

    @property
    def pending(self) -> int:
        with self._qlock:
            return len(self._queue) + len(self._orphans)

    @property
    def idle(self) -> bool:
        with self._qlock:
            return not self._outstanding

    def live_replicas(self) -> List[int]:
        with self._lock:
            return [r.idx for r in self._replicas if r.state == LIVE]

    def drain(self, timeout: float = 60.0) -> bool:
        """Wait until every submitted request concludes (FINISHED /
        FAILED / TIMEOUT); True iff all did within ``timeout``."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._qlock:
                reqs = list(self._outstanding.values())
            if not reqs:
                return True
            reqs[0].wait(min(0.05, max(deadline - time.monotonic(), 0.0)))
            with self._qlock:
                for rid in [r.rid for r in reqs if r.done]:
                    self._outstanding.pop(rid, None)
        with self._qlock:
            return not self._outstanding

    def warmup(self, prompt: Optional[Sequence[int]] = None,
               max_new_tokens: int = 2) -> None:
        """Compile every live replica's prefill bucket + decode step OFF
        the serving path (each replica engine has its own jit closures —
        compiles do not share). The silence detector cannot tell a long
        legitimate step (an XLA compile) from a wedge — that is inherent
        to the rc-117 contract — so warm the fleet before arming a tight
        ``heartbeat_timeout``, and keep the timeout above the worst-case
        legitimate step latency. While a replica warms its ``warming``
        flag exempts it from the SILENCE verdict (its worker is parked
        on the replica lock and cannot stamp; declaring the healthy
        warming replica dead would cause exactly the flap warmup
        prevents) — thread death is still detected. Restarted replicas
        are warmed before they rejoin (see ``_restart``) for the same
        reason."""
        prompt = list(prompt) if prompt is not None else [1, 2, 3]
        with self._lock:
            reps = [r for r in self._replicas if r.state == LIVE]
        for rep in reps:
            rep.warming = True
            try:
                with rep.lock:
                    if rep.state != LIVE or rep.engine is None:
                        continue
                    if self.disagg:
                        # role engines compile off-path without touching
                        # the real handoff (a warm item crossing roles
                        # would never conclude — it has no FleetRequest)
                        rep.engine.warm()
                    else:
                        # twice — zeros-pools AND donated-pools
                        # specializations (see _launch): the second
                        # compile must not land mid-serving
                        for _ in range(2):
                            rep.engine.submit(prompt, max_new_tokens)
                            rep.engine.run_until_idle()
                    if rep.writer is not None:
                        # fresh ts before the silence clock resumes
                        rep.writer.write(hb.PHASE_SERVE, rep.engine.steps,
                                         force=True)
            finally:
                rep.warming = False

    def generate_batch(self, prompts: Sequence[Sequence[int]],
                       max_new_tokens: int = 32, temperature: float = 0.0,
                       eos_token_id=None,
                       timeout: float = 120.0) -> List[List[int]]:
        """Convenience: submit all, drain, return outputs in order."""
        reqs = [self.submit(p, max_new_tokens, temperature,
                            eos_token_id=eos_token_id) for p in prompts]
        if not self.drain(timeout):
            raise RuntimeError(f"fleet did not drain within {timeout}s")
        return [r.output_tokens for r in reqs]

    # ---------------------------------------------------------- replica setup

    def _role(self, idx: int) -> Optional[str]:
        if not self.disagg:
            return None
        return "PREFILL" if idx < self.n_prefill else "DECODE"

    def _launch(self, rep: _Replica, warm: bool = False) -> None:
        if self.disagg:
            from .disagg import DecodeEngine, PrefillEngine
            if rep.idx < self.n_prefill:
                rep.engine = PrefillEngine(
                    self.cfg, self.params, serving=self.scfg,
                    shared=self._shared, handoff=self._handoff,
                    interpret=self.interpret)
            else:
                rep.engine = DecodeEngine(
                    self.cfg, self.params, serving=self.scfg,
                    shared=self._shared, handoff=self._handoff,
                    auto_pull=False, interpret=self.interpret)
        else:
            rep.engine = ServingEngine(self.cfg, self.params,
                                       serving=self.scfg,
                                       interpret=self.interpret)
        if warm:
            # a restarted replica must not rejoin until it can actually
            # serve: its fresh engine's decode compile would otherwise
            # read as heartbeat silence under a tight timeout and flap
            # the replica straight back to DOWN
            try:
                if self.disagg:
                    rep.engine.warm()
                else:
                    # TWICE: the first pass compiles against the fresh
                    # zero-initialized pools, the second against the
                    # DONATED committed pools every steady-state call
                    # uses — under some device contexts (e.g. a global
                    # mesh left by training code in-process) the two
                    # specialize separately, and the second compile must
                    # not land mid-serving where a tight
                    # heartbeat_timeout reads it as a wedge
                    for _ in range(2):
                        rep.engine.submit([1, 2, 3], 2)
                        rep.engine.run_until_idle()
            except Exception:
                logger.exception("fleet: replica %d warm-up failed",
                                 rep.idx)
        # refresh_interval=0: NO background re-stamper — a wedged replica
        # loop must read as silence (the whole point); the worker itself
        # is the liveness signal, min_interval paces the writes
        rep.writer = hb.HeartbeatWriter(
            self.heartbeat_dir, rank=rep.idx, host=f"replica-{rep.idx}",
            min_interval=float(self.fcfg.heartbeat_interval),
            refresh_interval=0.0)
        rep.started_ts = time.monotonic()
        # launch stamp: overwrite any previous generation's record (e.g.
        # the STALLED verdict of the engine this one replaces) so this
        # generation's silence is measured from ITS OWN record — a
        # terminal leftover would otherwise exempt a hung restart from
        # silence detection forever
        launch_gauges = {"queue": 0, "active": 0,
                         "lanes": int(self.scfg.max_batch)}
        if rep.engine.role is not None:
            launch_gauges["role"] = rep.engine.role
        rep.writer.write(hb.PHASE_SERVE, 0, force=True, extra=launch_gauges)
        rep.thread = threading.Thread(
            target=self._worker, args=(rep,),
            name=f"dstpu-fleet-replica-{rep.idx}", daemon=True)
        rep.thread.start()

    # ------------------------------------------------------------ worker loop

    def _worker(self, rep: _Replica) -> None:
        """One replica's serve loop: chaos gates, dispatch from the shared
        queue, one engine step, token sync, heartbeat stamp. ANY escape
        (chaos ``serve.replica_kill``, a real device failure) is replica
        death: record the error and fall silent — the supervisor detects,
        attributes and requeues. A loop wedged inside a step or failpoint
        (``serve.replica_hang``) is the silence case."""
        eng = rep.engine
        decode_role = self.disagg and rep.idx >= self.n_prefill
        try:
            while not self._stop.is_set() and rep.state == LIVE:
                # the iteration clock starts BEFORE the chaos gates so an
                # armed serve.replica_slow (sleep + every=/p= jitter —
                # degraded, not dead) inflates this replica's step_ms
                # gauge exactly like a thermal-throttled host would
                t_iter = time.monotonic()
                chaos.failpoint("serve.replica_hang", key=str(rep.idx))
                chaos.failpoint("serve.replica_kill", key=str(rep.idx))
                chaos.failpoint("serve.replica_slow", key=str(rep.idx))
                with rep.lock:
                    if rep.state != LIVE:
                        return
                    if decode_role:
                        self._dispatch_decode(rep)
                    else:
                        self._dispatch(rep)
                    worked = eng.has_work
                # the step runs OUTSIDE rep.lock: a wedge inside XLA must
                # not hold the lock the supervisor needs to fence this
                # replica — only the short dispatch/sync sections contend
                if worked:
                    eng.step()
                with rep.lock:
                    if rep.state != LIVE:
                        return          # fenced mid-step: the supervisor
                        #                 requeued our work; emitting now
                        #                 would double-fire tokens (a
                        #                 handoff pushed during the fenced
                        #                 step survives — its registration
                        #                 makes the teardown requeue skip
                        #                 it, and a decode replica serves
                        #                 the item exactly once)
                    if worked:
                        if self.disagg and not decode_role:
                            # drop handed-off requests from THIS replica's
                            # ledger BEFORE syncing: their tokens (the
                            # first token included) are emitted by the
                            # decode side only — one emitter per request
                            self._collect_handoffs(rep)
                        self._sync(rep)
                        # serving-iteration wall time (chaos gates +
                        # dispatch + step + sync): the straggler
                        # detector's cross-replica sample — idle spins
                        # are not steps and are not recorded
                        rep.step_clock.push_ms(
                            (time.monotonic() - t_iter) * 1000.0)
                    self._stamp(rep)
                if not worked:
                    time.sleep(0.005)
        except BaseException as e:     # noqa: BLE001 — death IS the contract
            rep.error = repr(e)
            logger.warning("fleet: replica %d loop died: %s", rep.idx, e)
            # no terminal stamp: a genuinely killed process could not
            # stamp either — the record goes silent / the thread dies,
            # and detection must work from that evidence alone

    def _dispatch(self, rep: _Replica) -> None:
        """Pull from the shared queue into this replica while it has free
        lanes and an empty engine queue (keeping the per-engine queue
        empty is the load-balancing: a request never waits on a busy
        replica while another has a free lane). Expired requests are shed
        here with TIMEOUT. Caller holds rep.lock. (Disagg: prefill-role
        replicas dispatch one request at a time — ``wants_dispatch`` —
        and decode-role replicas never dispatch from here at all.)
        A DRAINING replica (scale-down in flight) admits nothing — its
        lanes finish, then the supervisor retires it."""
        if rep.draining:
            return
        eng = rep.engine
        while eng.wants_dispatch:
            with self._qlock:
                req = self._queue.popnext()
            if req is None:
                return
            if req.expired():
                self._conclude(req, TIMEOUT,
                               "deadline exceeded while queued")
                continue
            if req.done:               # concluded while queued (close etc.)
                continue
            # the remaining TTL rides into the engine: a dispatched
            # request the replica cannot admit yet (block budget) is
            # still deadline-bounded by the ENGINE's shed — the fleet
            # queue can no longer see it
            dl = (max(req.deadline_ts - time.monotonic(), 0.0)
                  if req.deadline_ts is not None else None)
            try:
                er = eng.submit(req.prompt + req.output_tokens,
                                req.remaining,
                                temperature=req.temperature,
                                eos_token_id=req.eos_token_id,
                                deadline_s=dl, priority=req.priority)
            except BaseException:
                # an exploding enqueue (chaos serve.enqueue, engine-side
                # validation) kills THIS replica, but the popped request
                # must go back on the shared queue first — in neither
                # queue nor inflight it would be lost forever
                with self._qlock:
                    self._queue.appendleft(req)
                raise
            req.replica, req._synced = rep.idx, 0
            req.state = RUNNING
            rep.inflight[req.rid] = (req, er)
            if self.disagg:
                # push-time registration (on the prefill worker thread,
                # inside the engine step, WITHOUT rep.lock) resolves the
                # fleet request through this map instead of touching
                # replica state
                with self._qlock:
                    self._er2freq[er.rid] = req

    # ---------------------------------------------------- disagg role plumbing

    def _register_handoff(self, item) -> None:
        """BlockHandoff.on_push hook (runs under the handoff lock, on the
        pushing prefill worker's thread): record the item in the
        cross-role exactly-once ledger ATOMICALLY with the enqueue, so a
        decode replica can never pop an unregistered item, and a teardown
        requeue can never double-serve a pushed one."""
        er = item.req
        with self._qlock:
            freq = self._er2freq.pop(er.rid, None)
            if freq is not None:
                self._handoff_inflight[er.rid] = (freq, er)

    def _collect_handoffs(self, rep: _Replica) -> None:
        """Prefill worker post-step: requests pushed to the handoff this
        step leave THIS replica's inflight ledger UNCONDITIONALLY — the
        push itself moved ownership (registration is atomic with the
        enqueue), and a fast decode replica may have ALREADY popped the
        item and consumed the registration; keying the removal on the
        registration's presence would leave the request in BOTH
        replicas' ledgers with two workers racing the same ``_synced``
        cursor. Caller holds rep.lock."""
        for er in rep.engine.take_handed_off():
            for frid, (_freq, er2) in list(rep.inflight.items()):
                if er2 is er:
                    rep.inflight.pop(frid)
                    break

    def _dispatch_decode(self, rep: _Replica) -> None:
        """Decode worker: shed expired handoff items, then pop items into
        free lanes. The ``serve.handoff_drop`` failpoint fires between
        pop and install — a crash there is a decode-replica death with a
        popped item in hand: the request is already on rep.inflight (the
        death path requeues it through the token-exact prompt+emitted
        path) and the item's blocks ride ``rep.holding`` into the shared-
        pool quarantine. Caller holds rep.lock."""
        self._shed_handoff()
        eng = rep.engine
        while eng.lanes_free:
            item = self._handoff.pop()
            if item is None:
                return
            with self._qlock:
                pair = self._handoff_inflight.pop(item.req.rid, None)
                if pair is not None:
                    # takeover is ATOMIC with the pop: a prefill-replica
                    # teardown deciding whether to requeue this request
                    # reads (registration, owner) under the same lock, so
                    # it either sees the registration (skip) or sees this
                    # replica as owner (skip) — never a gap that would
                    # requeue a request a live decode replica is serving
                    pair[0].replica = rep.idx
            if pair is None or pair[0].done:
                # no live fleet request behind the item (concluded while
                # queued, or a close() edge): release and drop — blocks
                # must never leak the shared pool's accounting
                self._shared.pool.release(item.blocks)
                continue
            freq, er = pair
            rep.inflight[freq.rid] = (freq, er)
            rep.holding = item
            chaos.failpoint("serve.handoff_drop")
            rep.engine.install_item(item)
            rep.holding = None

    def _shed_handoff(self) -> None:
        """Deadline-aware handoff: conclude fleet requests whose items
        expired in the queue (runs at decode dispatch AND on the
        supervisor cadence — the latter covers a fleet with every decode
        replica down)."""
        for item in self._handoff.shed_expired():
            with self._qlock:
                pair = self._handoff_inflight.pop(item.req.rid, None)
            if pair is not None:
                self._conclude(pair[0], TIMEOUT,
                               "deadline exceeded in handoff queue")

    def _drain_quarantine(self) -> None:
        """Release dead disagg replicas' blocks into the SHARED pool once
        their worker threads are provably gone (supervisor cadence). A
        still-wedged engine (held_state timed out at teardown) is
        re-probed each pass; one wedged forever leaks its blocks — the
        same verdict the per-replica-pool design gives an abandoned
        worker, and the price of zero-copy sharing."""
        with self._qlock:
            pending, self._quarantine = self._quarantine, []
        keep = []
        for rep, blocks in pending:
            if blocks is None:
                hs = (rep.engine.held_state(timeout=0.2)
                      if rep.engine is not None else ([], []))
                if hs is None:
                    keep.append((rep, None))
                    continue
                blocks = list(hs[0])
                if rep.holding is not None:
                    blocks.append(rep.holding.blocks)
                    rep.holding = None
            if rep.thread is not None and rep.thread.is_alive():
                keep.append((rep, blocks))
                continue
            for bl in blocks:
                try:
                    self._shared.pool.release(bl)
                except ValueError:
                    logger.exception(
                        "fleet: quarantine release of replica %d blocks "
                        "found inconsistent refcounts", rep.idx)
        with self._qlock:
            self._quarantine.extend(keep)

    def _sync_one(self, req: FleetRequest, er) -> None:
        """Emit one request's newly generated tokens (the exactly-once
        cursor walk). Caller holds the owning replica's lock — worker
        sync, supervisor teardown and lane preemption all serialize
        here."""
        toks = er.output_tokens
        while req._synced < len(toks):
            tok = int(toks[req._synced])
            req._synced += 1
            req.output_tokens.append(tok)
            self._bump("tokens_emitted")
            if req.on_token is not None:
                try:
                    req.on_token(req, tok)
                except Exception:
                    logger.exception("fleet: on_token callback for "
                                     "request %d raised", req.rid)

    def _sync(self, rep: _Replica) -> None:
        """Emit newly generated tokens (exactly once — ``_sync_one`` is
        the only place fleet ``output_tokens`` grows) and conclude
        finished engine requests. Caller holds rep.lock; the supervisor
        flips state to DOWN under the same lock, so emission never races
        a requeue."""
        for rid in list(rep.inflight):
            req, er = rep.inflight[rid]
            self._sync_one(req, er)
            if er.done:
                del rep.inflight[rid]
                if self.disagg:
                    with self._qlock:
                        self._er2freq.pop(er.rid, None)
                if er.state == FAILED:
                    # deterministic per-request failure (the engine marked
                    # it before propagating would have killed the replica;
                    # reaching here means the engine concluded it cleanly)
                    self._conclude(req, FAILED, er.error)
                elif er.state == TIMEOUT:
                    self._conclude(req, TIMEOUT, er.error)
                else:
                    self._conclude(req, FINISHED)

    def _stamp(self, rep: _Replica) -> None:
        if rep.writer is None:
            return
        try:
            eng = rep.engine
            with self._qlock:
                qdepth = len(self._queue)
            gauges = {"queue": qdepth, "active": eng.active,
                      "lanes": eng.max_batch}
            rate = rep.step_clock.gauge()
            if rate is not None:
                gauges[STEP_MS_GAUGE] = rate
            if eng.role is not None:
                # PREFILL / DECODE visible in `dstpu health` (round 12)
                gauges["role"] = eng.role
                if self.disagg:
                    gauges["handoff"] = self._handoff.pending
            rep.writer.write(hb.PHASE_SERVE, eng.steps, extra=gauges)
        except Exception:
            pass                        # diagnostics must not kill a replica

    def _bump(self, key: str, n: int = 1) -> None:
        # dict += from N worker threads + the supervisor is a lost-update
        # race; every counter goes through this one lock
        with self._stats_lock:
            self.stats[key] += n

    def _conclude(self, req: FleetRequest, state: str,
                  error: Optional[str] = None) -> None:
        if req._finish(state, error):
            self._bump({FINISHED: "completed", FAILED: "failed",
                        TIMEOUT: "timeout", SHED: "shed"}[state])
        with self._qlock:
            self._outstanding.pop(req.rid, None)

    # ------------------------------------------------- death handling (called
    # by FleetSupervisor; the mechanics live here, the detection there)

    def _replica_down(self, rep: _Replica, reason: str,
                      evidence: Optional[dict]) -> None:
        """Tear down ONE replica: mark DOWN under its lock (fencing any
        late token sync — the worker re-checks state under the same lock
        before emitting, and steps run outside it, so this acquire only
        ever waits on the short dispatch/sync sections), stamp STALLED
        evidence, requeue its in-flight requests, then
        strike/blacklist/restart.

        If the lock cannot be acquired (the worker is wedged INSIDE its
        critical section — e.g. a blocked user on_token callback), the
        replica is only FENCED (state -> DOWN; the worker exits at its
        next state check) and the teardown is parked for the next poll:
        requeueing while the wedged worker could still wake and emit
        would double-fire tokens, and exactly-once beats promptness. A
        section wedged forever defers its requests forever — the same
        verdict a process-wide wedge earns from the rc-117 stack."""
        if not rep.lock.acquire(timeout=5.0):
            rep.state = DOWN
            with self._qlock:
                self._pending_down.append((rep, reason, evidence))
            logger.warning(
                "fleet: replica %d fenced but wedged inside its critical "
                "section — teardown deferred", rep.idx)
            return
        try:
            if rep.state == DOWN:
                pass                    # parked teardown: finish it now
            elif rep.state != LIVE:
                return                  # already fully handled
            rep.state = DOWN
            inflight = list(rep.inflight.values())
            rep.inflight.clear()
            if self.disagg:
                # the dead replica's share of the SHARED pool (decode
                # lanes / half-prefilled chunks / a popped-but-
                # uninstalled item) is detached NOW — under the replica
                # lock, so the worker can't be mid-dispatch — and
                # released only once the thread is provably dead (the
                # abandoned final step may still write through its old
                # tables): _drain_quarantine on the supervisor cadence
                hs = (rep.engine.held_state(timeout=1.0)
                      if rep.engine is not None else ([], []))
                q_blocks = None if hs is None else list(hs[0])
                if q_blocks is not None and rep.holding is not None:
                    q_blocks.append(rep.holding.blocks)
                    rep.holding = None
                with self._qlock:
                    self._quarantine.append((rep, q_blocks))
        finally:
            rep.lock.release()
        rep.strikes += 1
        self._bump("deaths")
        if rep.writer is not None:
            # the verdict, durable: dstpu health shows STALLED for this
            # replica until a restart generation overwrites the rank file
            rep.writer.stamp_terminal(hb.PHASE_STALLED, lock_timeout=1.0)
        death = {"replica": rep.idx, "generation": rep.generation,
                 "reason": reason, "error": rep.error, "evidence": evidence,
                 "strikes": rep.strikes, "detected_ts": time.monotonic(),
                 "action": None, "restarted_ts": None}
        self.deaths.append(death)
        logger.warning(
            "fleet: replica %d DOWN (%s; strike %d): last heartbeat %s",
            rep.idx, reason, rep.strikes,
            "none" if evidence is None else
            f"phase={evidence.get('phase')} step={evidence.get('step')}")
        # reversed: each requeue appendlefts, so walking newest-first
        # leaves the earliest-admitted request at the queue HEAD —
        # FIFO standing preserved across the teardown
        for req, er in reversed(inflight):
            self._requeue(req, er, from_idx=rep.idx)
        if rep.draining:
            # the replica was already being scaled down: its death just
            # ends the drain early — lanes requeued exactly-once above,
            # and the autoscaler wanted the capacity gone, so no strike
            # toward blacklist and no replacement
            death["action"] = "retired"
            self._note_drained(rep, clean=False)
            return
        blacklist_after = int(self.fcfg.blacklist_after)
        if blacklist_after > 0 and rep.strikes >= blacklist_after:
            rep.state = BLACKLISTED
            with self._lock:
                self._replicas[rep.idx] = rep
            self._bump("blacklisted")
            death["action"] = "blacklist"
            logger.warning("fleet: replica %d BLACKLISTED after %d strikes",
                           rep.idx, rep.strikes)
            return
        # the decision is recorded BEFORE the (warm-including, slow)
        # relaunch: readers draining on survivors must see the verdict
        # as soon as it is made, not after the replacement compiled
        death["action"] = "restart"
        self._restart(rep.idx, rep.generation + 1, rep.strikes)
        death["restarted_ts"] = time.monotonic()

    def _replica_drain(self, rep: _Replica, evidence: Optional[dict]
                       ) -> None:
        """Straggler remediation, fleet-side (runtime/straggler.py): a
        replica the cross-replica detector verdicted SLOW is DRAINED
        through the existing death path — admission stops (the DOWN
        fence), its in-flight lanes requeue through the exactly-once
        token-exact path, the strike counts toward ``blacklist_after``,
        and the replacement restarts warmed — instead of letting one
        throttled replica hold the shared queue's p99 hostage. The
        sticky STRAGGLER flag lands on the record BEFORE the STALLED
        verdict so ``dstpu health`` (and the death ledger's evidence)
        names the reason, the SDC-flag pattern."""
        logger.warning(
            "fleet: replica %d is a straggler (step_ms %s vs the fleet) "
            "— draining", rep.idx,
            (evidence or {}).get("gauges", {}).get(STEP_MS_GAUGE))
        if rep.writer is not None:
            rep.writer.add_flag(STRAGGLER_FLAG, lock_timeout=1.0)
        self._replica_down(rep, "straggler", evidence)

    def _requeue(self, req: FleetRequest, er,
                 from_idx: Optional[int] = None,
                 charge_retry: bool = True) -> None:
        """Exactly-once requeue: conclude what the dead replica already
        concluded, finish requests whose budget is spent, retry-budget
        the rest back onto the queue HEAD of their tier (they were
        admitted first — FIFO standing is preserved). ``from_idx`` names
        the dying replica (None for orphan retries): a disagg request
        whose owner moved past it — pushed into the handoff, or already
        popped by a decode replica — is NOT requeued.
        ``charge_retry=False`` is the preemption path (round 19): a
        batch lane evicted for a pressured latency request lost nothing
        to a failure, so the eviction must not march it toward a FAILED
        verdict. ``serve.requeue`` crashes here park the request on the
        orphan list for the next supervisor poll."""
        try:
            chaos.failpoint("serve.requeue")
            if self.disagg and er is not None:
                with self._qlock:
                    self._er2freq.pop(er.rid, None)
                    handed = er.rid in self._handoff_inflight
                    taken_over = (from_idx is not None
                                  and req.replica is not None
                                  and req.replica != from_idx)
                if handed or taken_over:
                    # the dying prefill replica's push DID land (fenced
                    # mid-step): either the item still sits registered in
                    # the handoff queue, or a decode replica already
                    # popped it and took ownership (assignment atomic
                    # with the pop under _qlock) — it will be served
                    # exactly once there; requeueing the request too
                    # would serve it twice
                    return
                if er.prefill_progress:
                    # chunk progress carried: how far the dead leg's
                    # prefill got, for the death ledger / observability
                    req.prefill_progress = int(er.prefill_progress)
            if er is not None and er.done and er.state in (FAILED, TIMEOUT):
                self._conclude(req, er.state, er.error)
                return
            if (req.remaining <= 0
                    or (req.eos_token_id is not None and req.output_tokens
                        and req.output_tokens[-1] == req.eos_token_id)):
                self._conclude(req, FINISHED)
                return
            if req.expired():
                self._conclude(req, TIMEOUT, "deadline exceeded at requeue")
                return
            if charge_retry:
                req.retries += 1
            if req.retries > int(self.fcfg.retry_budget):
                self._conclude(
                    req, FAILED,
                    f"retry budget exhausted ({self.fcfg.retry_budget} "
                    f"requeues) after replica failures")
                return
            req.replica, req.state, req._synced = None, QUEUED, 0
            with self._qlock:
                self._queue.appendleft(req)
            self._bump("requeues")
        except chaos.ChaosError as e:
            logger.warning("fleet: requeue of request %d failed (%s) — "
                           "orphaned for retry", req.rid, e)
            with self._qlock:
                self._orphans.append(req)

    def _retry_orphans(self) -> None:
        with self._qlock:
            orphans, self._orphans = self._orphans, []
        for req in orphans:
            self._requeue(req, None)

    def _shed_expired(self) -> None:
        # ONE `now` for both passes: a deadline crossing between the
        # partitioning comprehensions would otherwise drop a request
        # from the queue without ever concluding it
        now = time.monotonic()
        with self._qlock:
            expired = self._queue.remove_expired(now)
        for req in expired:
            self._conclude(req, TIMEOUT, "deadline exceeded while queued")

    def _restart(self, idx: int, generation: int, strikes: int,
                 parole: bool = False) -> None:
        fresh = _Replica(idx, generation=generation, strikes=strikes)
        with self._lock:
            self._replicas[idx] = fresh
        self._bump("restarts")           # counted at initiation: observers
        if parole:                       # must not wait out the warm-up
            self._bump("paroles")
        self._launch(fresh, warm=True)
        logger.warning("fleet: replica %d %s (generation %d)",
                       idx, "PAROLED" if parole else "restarted", generation)

    def _maybe_parole(self) -> None:
        """Capacity floor: with live replicas below ``min_replicas``,
        parole the least-struck blacklisted replica back (strikes stand —
        it can be re-blacklisted) rather than serving starved."""
        with self._lock:
            live = sum(1 for r in self._replicas if r.state == LIVE)
            candidates = [r for r in self._replicas
                          if r.state == BLACKLISTED]
        if live >= int(self.fcfg.min_replicas) or not candidates:
            return
        victim = min(candidates, key=lambda r: (r.strikes, r.idx))
        self._restart(victim.idx, victim.generation + 1, victim.strikes,
                      parole=True)

    # ------------------------------------------------- traffic shaping (round
    # 19: autoscaling + preemption; the POLICY lives in serving/autoscale.py,
    # these are the mechanisms the supervisor drives each poll)

    def _autoscale_tick(self) -> None:
        """Feed this poll's gauges — the same numbers the replicas stamp
        into their SERVE heartbeats — through the AutoscalePolicy and
        perform its verdict. Also completes any drain in flight."""
        if self.autoscale is None:
            return
        now = time.monotonic()
        with self._lock:
            reps = list(self._replicas)
        serving = [r for r in reps if r.state == LIVE
                   and not r.draining and not r.warming]
        warming = sum(1 for r in reps if r.state == LIVE and r.warming)
        draining = [r for r in reps if r.state == LIVE and r.draining]
        for rep in draining:
            self._finish_drain(rep)
        with self._qlock:
            qdepth = len(self._queue)
            pressured = self._queue.pressured(
                float(self.fcfg.autoscale.pressure_s), now)
        active = sum(r.engine.active for r in serving
                     if r.engine is not None)
        obs = Observation(
            queue_depth=qdepth, pressured=pressured, live=len(serving),
            warming=warming, draining=len(draining), active_lanes=active,
            total_lanes=len(serving) * int(self.scfg.max_batch))
        verdict = self.autoscale.observe(obs, now)
        if verdict == SCALE_UP:
            self._scale_up(self.autoscale.describe(obs), obs)
        elif verdict == SCALE_DOWN:
            self._scale_down(self.autoscale.describe(obs), obs)

    def _scale_up(self, reason: str, obs: Observation) -> None:
        """Append a NEW replica slot and launch it WARMED (the restart
        path's warm=True): it compiles off-path and only then starts its
        worker — scaled-up capacity never serves cold, and its compile
        cannot read as heartbeat silence. The ``serve.scale_up``
        failpoint crashes inside the spawn: the slot rolls back and the
        event records ``up_failed`` — a failed spawn leaves the fleet
        exactly as it was (no phantom replica) and still starts the
        cooldown (the overload that caused it is still being answered)."""
        with self._lock:
            idx = len(self._replicas)
            rep = _Replica(idx)
            self._replicas.append(rep)
        event = ScaleEvent(action=SCALE_UP, replica=idx, reason=reason,
                           ts=time.monotonic(), queue=obs.queue_depth,
                           live=obs.live)
        try:
            chaos.failpoint("serve.scale_up", key=str(idx))
            self._launch(rep, warm=True)
        except Exception as e:
            with self._lock:
                if self._replicas and self._replicas[-1] is rep:
                    self._replicas.pop()
            event.action = "up_failed"
            event.error = repr(e)
            self.scale_events.append(event)
            self._stamp_autoscaler(force=True)
            logger.warning("fleet: scale-up of replica %d failed: %s",
                           idx, e)
            return
        self._bump("scale_ups")
        self.scale_events.append(event)
        self._stamp_autoscaler(force=True)
        logger.warning("fleet: scaled UP to replica %d (%s)", idx, reason)

    def _scale_down(self, reason: str, obs: Observation) -> None:
        """Start draining the NEWEST serving replica (LIFO keeps the
        original fleet's indices stable): admission stops now, its lanes
        finish, and ``_finish_drain`` retires it — the straggler-drain
        discipline without the strike. The event is recorded at
        initiation (``drained_ts`` lands at completion), so `dstpu
        health` shows the drain while it is in flight."""
        with self._lock:
            cands = [r for r in self._replicas if r.state == LIVE
                     and not r.draining and not r.warming]
        if len(cands) <= self.autoscale.min_replicas:
            return
        rep = max(cands, key=lambda r: r.idx)
        rep.draining = True
        self.scale_events.append(ScaleEvent(
            action=SCALE_DOWN, replica=rep.idx, reason=reason,
            ts=time.monotonic(), queue=obs.queue_depth, live=obs.live))
        self._stamp_autoscaler(force=True)
        logger.warning("fleet: scaling DOWN replica %d (%s) — draining",
                       rep.idx, reason)

    def _finish_drain(self, rep: _Replica) -> None:
        """Retire a draining replica once its lanes emptied: state flips
        to RETIRED under the replica lock (the worker exits at its next
        state check; a step cannot be in flight for an idle engine) and
        the EXIT terminal stamp — not STALLED — records a conclusion,
        not a failure. A still-busy or lock-contended drain just waits
        for the next poll; a draining replica that DIES instead goes
        through ``_replica_down`` (exactly-once requeue, no restart)."""
        if rep.inflight or (rep.engine is not None
                            and rep.engine.has_work):
            return
        if not rep.lock.acquire(timeout=1.0):
            return
        try:
            if rep.state != LIVE or not rep.draining:
                return
            if rep.inflight or rep.engine.has_work:
                return
            rep.state = RETIRED
        finally:
            rep.lock.release()
        if rep.writer is not None:
            rep.writer.stamp_terminal(hb.PHASE_EXIT, lock_timeout=1.0)
        self._note_drained(rep, clean=True)
        logger.warning("fleet: replica %d RETIRED (drain complete)",
                       rep.idx)

    def _note_drained(self, rep: _Replica, clean: bool) -> None:
        """Conclude the replica's scale-down event in the capacity
        ledger (``clean=False``: the drain ended by death — its lanes
        requeued exactly-once rather than finishing in place)."""
        self._bump("scale_downs")
        for ev in reversed(self.scale_events):
            if ev.action == SCALE_DOWN and ev.replica == rep.idx \
                    and ev.drained_ts is None:
                ev.drained_ts = time.monotonic()
                if not clean:
                    ev.error = "drain ended by replica death"
                break
        self._stamp_autoscaler(force=True)

    def _stamp_autoscaler(self, force: bool = False) -> None:
        """The autoscaler's heartbeat record: refreshed every supervisor
        poll (so it never reads as silent while supervised) and forced
        on every scale event — `dstpu health` shows the last verdict in
        the gauges column alongside the replicas it acted on."""
        if self._as_writer is None:
            return
        try:
            with self._qlock:
                qdepth = len(self._queue)
            with self._lock:
                live = sum(1 for r in self._replicas
                           if r.state == LIVE and not r.draining)
            gauges = {"role": "AUTOSCALER", "queue": qdepth, "live": live,
                      "events": len(self.scale_events)}
            if self.scale_events:
                ev = self.scale_events[-1]
                gauges["event"] = f"{ev.action}@r{ev.replica}"
            self._as_writer.write(hb.PHASE_SERVE, len(self.scale_events),
                                  force=force, extra=gauges)
        except Exception:
            pass                        # diagnostics must not kill a poll

    def _maybe_preempt(self) -> None:
        """Deadline-pressured latency admission (round 19): when a
        latency-tier request is queued within ``preempt_pressure_s`` of
        its deadline and NO serving replica has a free lane, evict the
        youngest RUNNING batch-tier lane and requeue it through the
        exactly-once token-exact path (emitted prefix carried, no
        retry-budget charge) — the freed lane admits the pressured
        request at the owner's next dispatch. At most one eviction per
        poll bounds the churn. The ``serve.preempt`` failpoint fires in
        the window between eviction and requeue: a crash there parks the
        victim on the orphan list — deferred, never lost, never
        double-emitted (its lane is gone and its cursor was synced under
        the replica lock)."""
        window = float(self.fcfg.preempt_pressure_s)
        if window <= 0 or self.disagg:
            return
        now = time.monotonic()
        with self._qlock:
            pressured = next(
                (r for r in self._queue
                 if r.priority == LATENCY and r.deadline_ts is not None
                 and 0.0 <= (r.deadline_ts - now) < window), None)
        if pressured is None:
            return
        with self._lock:
            reps = [r for r in self._replicas
                    if r.state == LIVE and not r.draining]
        if any(r.engine is not None and r.engine.wants_dispatch
               for r in reps):
            return                       # a free lane will serve it
        for rep in reps:
            if not rep.lock.acquire(timeout=1.0):
                continue
            try:
                if rep.state != LIVE:
                    continue
                victim = None
                for freq, er in rep.inflight.values():
                    if freq.priority == BATCH and er.state == RUNNING \
                            and (victim is None
                                 or freq.arrival_ts > victim[0].arrival_ts):
                        victim = (freq, er)
                if victim is None:
                    continue
                freq, er = victim
                # sync BEFORE evicting: tokens the engine already
                # generated are emitted (the healthy-replica economy the
                # death path cannot have), then the eviction drops only
                # lane state — the requeue resumes from prompt+emitted
                self._sync_one(freq, er)
                if not rep.engine.preempt_request(er, timeout=1.0):
                    continue
                rep.inflight.pop(freq.rid, None)
                freq.preemptions += 1
                self._bump("preempted")
                logger.warning(
                    "fleet: preempting batch request %d on replica %d "
                    "for pressured latency request %d", freq.rid,
                    rep.idx, pressured.rid)
                try:
                    chaos.failpoint("serve.preempt")
                except chaos.ChaosError as e:
                    logger.warning(
                        "fleet: preemption requeue of request %d failed "
                        "(%s) — orphaned for retry", freq.rid, e)
                    with self._qlock:
                        self._orphans.append(freq)
                    return
                self._requeue(freq, None, from_idx=rep.idx,
                              charge_retry=False)
                return
            finally:
                rep.lock.release()


class FleetSupervisor:
    """Consumes the fleet's heartbeat channel and replica thread liveness;
    detection only — teardown/requeue mechanics live on the fleet.

    DOWN verdicts, in evidence order:

    * a dead worker thread (the in-process analog of a rank exit) — the
      last heartbeat record is the attribution;
    * ``heartbeat_timeout`` seconds of record silence from a live thread
      (rc-117 contract: the record is non-terminal and stale, or the
      replica never wrote despite ``heartbeat_timeout`` since launch) —
      the wedge/hang case.

    ``poll()`` is the public deterministic entry (tests call it
    directly); ``start()`` runs it on a daemon thread every
    ``poll_interval`` seconds. Each poll also retries orphaned requeues,
    sheds expired queued requests, and applies the parole floor."""

    def __init__(self, fleet: ServingFleet):
        self.fleet = fleet
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # straggler drain (round 15): the cross-rank relative-slowness
        # detector over the replicas' step_ms SERVE gauges — fleet.
        # straggler.enabled opts in (getattr: verdict-unit tests build
        # the supervisor over a bare fcfg namespace)
        scfg = getattr(fleet.fcfg, "straggler", None)
        self._straggler: Optional[StragglerDetector] = (
            StragglerDetector(scfg)
            if scfg is not None and scfg.enabled else None)

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="dstpu-fleet-supervisor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self) -> None:
        interval = max(float(self.fleet.fcfg.poll_interval), 0.01)
        while not self._stop.wait(interval):
            try:
                self.poll()
            except Exception:
                logger.exception("fleet supervisor poll failed")

    # ------------------------------------------------------------- detection

    def poll(self) -> List[dict]:
        """One supervision pass; returns the deaths it declared (a
        fenced-but-wedged teardown records its death only once its lock
        frees, possibly on a later poll — the ledger snapshot below
        captures whichever pass it lands on)."""
        fleet = self.fleet
        records = hb.read_heartbeats(fleet.heartbeat_dir)
        now = time.monotonic()
        n_deaths = len(fleet.deaths)
        with fleet._lock:
            reps = list(fleet._replicas)
        # finish any fenced-but-wedged teardowns first: their lock may
        # have freed (worker exited at its DOWN fence) since last poll
        with fleet._qlock:
            pending, fleet._pending_down = fleet._pending_down, []
        for rep, reason, ev in pending:
            fleet._replica_down(rep, reason, ev)
        for rep in reps:
            if rep.state != LIVE:
                continue
            evidence = records.get(rep.idx)
            verdict = self._verdict(rep, evidence, now)
            if verdict is not None:
                fleet._replica_down(rep, verdict, evidence)
        if self._straggler is not None:
            self._check_stragglers(reps, records)
        fleet._retry_orphans()
        fleet._shed_expired()
        fleet._maybe_preempt()
        fleet._autoscale_tick()
        fleet._stamp_autoscaler()
        if fleet.disagg:
            # handoff deadlines must hold even with every decode replica
            # down, and dead replicas' shared-pool blocks release once
            # their threads are provably gone
            fleet._shed_handoff()
            fleet._drain_quarantine()
        fleet._maybe_parole()
        return list(fleet.deaths[n_deaths:])

    def _check_stragglers(self, reps: List[_Replica],
                          records: Dict[int, dict]) -> None:
        """One straggler observation window over the LIVE replicas'
        step_ms gauges (runtime/straggler.py): a verdicted replica is
        drained through the replica-death path. Warming replicas are
        excluded — their frozen pre-warm gauge measures nothing."""
        live = {r.idx: r for r in reps
                if r.state == LIVE and not r.warming and not r.draining}
        snapshot = {idx: rec for idx, rec in records.items()
                    if idx in live}
        for idx in self._straggler.observe(snapshot):
            rep = live.get(idx)
            if rep is None or rep.state != LIVE:
                continue
            self._straggler.forget(idx)   # the replacement starts clean
            self.fleet._replica_drain(rep, records.get(idx))

    def _verdict(self, rep: _Replica, evidence: Optional[dict],
                 now: float) -> Optional[str]:
        if rep.thread is not None and not rep.thread.is_alive():
            return "crash"
        if rep.warming:
            # warmup() holds the replica lock through an XLA compile;
            # the parked worker cannot stamp — silence is expected and
            # healthy here (thread death above still applies)
            return None
        timeout = float(self.fleet.fcfg.heartbeat_timeout)
        if timeout <= 0:
            return None
        if evidence is None:
            # expected-but-never-wrote: launched long enough ago that the
            # first loop iteration's stamp is overdue (PR-6's
            # BackendSupervisor expected_ranks case, fleet-side)
            if now - rep.started_ts > timeout:
                return "silence"
            return None
        if evidence.get("phase") in hb.TERMINAL_PHASES:
            return None                 # a conclusion, not silence
        if hb.record_age(evidence) > timeout:
            return "silence"
        return None
