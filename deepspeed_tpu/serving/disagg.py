"""Disaggregated serving: a PrefillEngine/DecodeEngine pair over a
paged-KV block handoff (round 12, ROADMAP item 1 rung (b)).

Prefill and decode are different regimes — prefill is compute-bound over
a whole prompt, decode is bandwidth-bound one token at a time — and
PR 8's engine interleaves them in one loop, so every prefill (even
chunked) steals iterations from running decodes. This module splits the
two into ROLES connected by a :class:`BlockHandoff`:

* the **prefill role** (:class:`PrefillEngine`) admits requests under the
  shared block budget, runs CHUNKED prefill (``serving.
  prefill_chunk_tokens`` per iteration — the round-12 engine machinery),
  samples the first token from the last real position's logits, and
  pushes a :class:`HandoffItem` — the request, its refcounted block IDs,
  block table, context length and sampler state (first token, emitted
  prefix) — onto the handoff queue;
* the **decode role** (:class:`DecodeEngine`) pops finished items,
  installs them into its fixed-shape decode lanes, and decodes — its
  compiled decode step stays the loop's ONLY specialization (compile
  count 1, pinned by test).

**Zero-copy by construction.** Both roles share ONE
:class:`~.kv_cache.SharedPagedState` — device pool, refcounted
:class:`~.kv_cache.BlockPool`, prefix cache — so the handoff transfers
block *ownership* (a list of ints plus sampler state), never KV bytes.
The refcounted block table from PR 8 is the transfer unit; no logical
state is copied. The roles' jitted calls serialize on the shared state's
device lock (both donate the pool buffers).

**Bounded and deadline-aware.** The queue holds at most
``serving.handoff_queue`` items — a full queue stalls prefill (the item
is retried next iteration; backpressure, never a drop) — and an item
whose request deadline passes while it waits is SHED: blocks released,
request concluded TIMEOUT (handoff wait is queue wait the request's
deadline already bounds).

**Failure domains** (the fleet wires roles as replicas —
``serving.fleet.prefill_replicas`` / ``decode_replicas``; see
serving/fleet.py): a dead prefill replica releases its half-prefilled
request's blocks and requeues it exactly-once (chunk progress carried on
``Request.prefill_progress``); a dead decode replica requeues through
the existing token-exact prompt+emitted path. Chaos failpoints:
``serve.chunk`` (per prefill chunk, in serving/engine.py),
``serve.handoff`` (inside :meth:`BlockHandoff.push`, before the item is
queued — a crash there leaves the blocks with the dying prefill role),
``serve.handoff_drop`` (between pop and install — a crash there leaves a
popped item with the dying decode role). The crash-at-every-failpoint
matrix in tests/test_disagg.py pins that every request still concludes
COMPLETED (token-exact) or FAILED-within-retry-budget and that the
pool's free+refcounted accounting balances after recovery.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ..runtime.fabric import ChannelTimeout, LocalEndpoint
from ..testing import chaos
from ..utils.logging import logger
from .engine import ServingEngine, _Seq, resolve_kv_dtype
from .kv_cache import SharedPagedState
from .scheduler import HANDOFF, RUNNING, TIMEOUT, Request

PyTree = Any


class HandoffFull(RuntimeError):
    """The bounded handoff queue is at capacity — the prefill role's
    signal to hold the finished item and retry (backpressure), never to
    drop it."""


@dataclass
class HandoffItem:
    """One finished prefill crossing the prefill->decode boundary: block
    ownership (IDs into the SHARED pool — zero-copy) plus the sampler
    state decode resumes from (``last_tok`` = the first sampled token,
    already on ``req.output_tokens`` as the emitted prefix; ``ctx`` = the
    next-token logits position, i.e. the prompt length)."""
    req: Request
    blocks: List[int]
    table: np.ndarray
    ctx: int
    last_tok: int
    enqueue_ts: float = field(default_factory=time.monotonic)


class BlockHandoff:
    """The bounded, deadline-aware prefill->decode queue (module
    docstring). ``on_push`` (the fleet's registration hook) runs under
    the queue lock, so a consumer can never pop an item before its
    producer-side bookkeeping exists."""

    def __init__(self, pool, capacity: int = 16,
                 on_push: Optional[Callable[[HandoffItem], None]] = None):
        self.pool = pool
        self.capacity = int(capacity)
        self.on_push = on_push
        # the queue is a local fabric endpoint (round 18): items ride
        # BY REFERENCE — ownership transfer, never KV bytes — and every
        # push/pop traverses the fabric's net.* chaos surface, the same
        # failure model the cross-process backends exercise
        self._ep = LocalEndpoint(ident="handoff")
        self._mu = threading.Lock()
        self.pushed = 0
        self.popped = 0
        self.timed_out = 0

    @property
    def pending(self) -> int:
        return self._ep.pending()

    def push(self, item: HandoffItem) -> None:
        """Enqueue a finished prefill. The ``serve.handoff`` failpoint —
        and the fabric's ``net.send`` below it — fires BEFORE the item
        is queued or its state mutated: a crash there leaves the blocks
        owned by the (dying) prefill role, whose death path releases
        them — the item is never half-queued. Raises :class:`HandoffFull`
        at capacity."""
        chaos.failpoint("serve.handoff")
        with self._mu:
            if self._ep.pending() >= self.capacity:
                raise HandoffFull(
                    f"handoff queue at capacity ({self.capacity}); "
                    "decode is behind — prefill holds the item")
            self._ep.send({"kind": "handoff", "rid": item.req.rid},
                          item, key="handoff")
            item.req.state = HANDOFF
            self.pushed += 1
            if self.on_push is not None:
                self.on_push(item)

    def pop(self) -> Optional[HandoffItem]:
        # bounded acquire: recv(timeout=0) is a non-blocking poll, but a
        # wedged chaos hook inside it must not hold _mu against push and
        # shed forever — a starved pop returns None like an empty queue
        if not self._mu.acquire(timeout=5.0):
            return None
        try:
            try:
                _meta, item = self._ep.recv(timeout=0.0, key="handoff")
            except ChannelTimeout:
                return None
            self.popped += 1
            return item
        finally:
            self._mu.release()

    def shed_expired(self) -> List[HandoffItem]:
        """Deadline-aware: conclude every queued item whose request
        deadline has passed — blocks released, request TIMEOUT (callback
        fires). Handoff wait is queue wait; the same TTL that bounds
        admission wait bounds it."""
        now = time.monotonic()
        with self._mu:
            expired = [it for _m, it in self._ep.purge(
                lambda _meta, it: it.req.expired(now))]
            self.timed_out += len(expired)
        for it in expired:
            self.pool.release(it.blocks)
            logger.warning("disagg: request %d shed from the handoff "
                           "queue past its deadline", it.req.rid)
            it.req._finish(TIMEOUT,
                           error="deadline exceeded in handoff queue")
        return expired

    def drain_release(self) -> int:
        """Shutdown path: release every queued item's blocks (their
        requests are left to the owner to conclude). Returns items
        drained."""
        n = 0
        while True:
            item = self.pop()
            if item is None:
                return n
            try:
                self.pool.release(item.blocks)
            except ValueError:
                logger.exception("disagg: drain found inconsistent "
                                 "handoff blocks")
            n += 1


class PrefillEngine(ServingEngine):
    """The prefill ROLE: chunked prefill into the SHARED pool, handoff on
    completion. Never decodes — its lanes stay empty and its compiled
    decode step is never traced. One request prefills at a time (the
    chunk machinery's invariant); a finished item that hits a full
    handoff queue is held and retried (``_ready``), with admission paused
    behind it."""

    role = "PREFILL"

    def __init__(self, cfg, params, serving=None, *, shared: SharedPagedState,
                 handoff: BlockHandoff, **kw):
        super().__init__(cfg, params, serving=serving, shared=shared, **kw)
        self.handoff = handoff
        self._ready: Optional[_Seq] = None    # finished, awaiting queue room
        self._handed: List[Request] = []      # pushed since last take_*

    # the prefill role ALWAYS runs the chunk machinery (chunk <= 0 means
    # one whole-suffix chunk) so completion flows through _install
    def _chunked_mode(self) -> bool:
        return True

    def _admission_capacity(self) -> bool:
        return self._ready is None

    @property
    def idle(self) -> bool:
        return (self.scheduler.pending == 0 and self._prefilling is None
                and self._ready is None)

    @property
    def has_work(self) -> bool:
        return bool(self.scheduler.pending or self._prefilling is not None
                    or self._ready is not None)

    @property
    def wants_dispatch(self) -> bool:
        return (self.scheduler.pending == 0 and self._prefilling is None
                and self._ready is None)

    def _install(self, seq: _Seq) -> None:
        self._ready = seq
        self._flush_ready()

    def _flush_ready(self) -> None:
        seq = self._ready
        if seq is None:
            return
        item = HandoffItem(req=seq.req, blocks=seq.blocks, table=seq.table,
                           ctx=seq.ctx, last_tok=seq.last_tok)
        try:
            self.handoff.push(item)
        except HandoffFull:
            return                        # backpressure: retry next step
        self._ready = None
        self._handed.append(seq.req)

    def take_handed_off(self) -> List[Request]:
        """Requests pushed since the last call (the fleet worker's
        post-step bookkeeping hook)."""
        out, self._handed = self._handed, []
        return out

    def step(self) -> int:
        with self._lock:
            self._flush_ready()           # a backpressured item first
            done = self._admit()
            done += self._advance_prefill()
            self.steps += 1
            self.stats["timeout"] = self.scheduler.timed_out
            self._stamp_heartbeat()
            return done

    def warm(self) -> None:
        """Compile the chunk-bucket prefill program off the serving path
        (nothing reaches the handoff: a 1-token-budget request concludes
        at prefill end and releases its blocks). Runs TWICE: the first
        pass compiles against the fresh zero-initialized pools, the
        second against the donated committed pools steady-state chunks
        use — under some device contexts (a global mesh left in-process)
        they specialize separately, and the second compile must not land
        mid-serving where a tight heartbeat timeout reads it as a
        wedge. Warm requests leave NO trace: no prefix-cache inserts
        (``_warming`` gates them — a dummy prompt must not hold shared
        pool blocks hostage per restart) and stats are restored (phantom
        'completed' requests would pollute fleet throughput
        accounting)."""
        with self._lock:
            n = max(self._chunk, 3)
            saved = dict(self.stats)
            self._warming = True
            try:
                for _ in range(2):
                    pf = self._start_prefill(Request(prompt=[1] * n,
                                                     max_new_tokens=1))
                    self._prefilling = pf
                    while self._prefilling is not None:
                        self._advance_prefill()
            finally:
                self._warming = False
                self.stats.update(saved)

    def _collect_held(self, blocks, reqs) -> None:
        if self._ready is not None:
            blocks.append(self._ready.blocks)
            reqs.append(self._ready.req)
            self._ready = None


class DecodeEngine(ServingEngine):
    """The decode ROLE: pops handoff items into its fixed-shape lanes and
    decodes. Its compiled decode step is the ONLY program it ever traces
    (compile count 1, pinned); it never allocates blocks — ownership
    arrives with the item, and :meth:`ServingEngine._finish` releases to
    the shared pool.

    ``auto_pull=False`` (the fleet) moves the pop/install into the
    fleet's dispatch section so installs are fenced by the replica lock;
    standalone (:class:`DisaggEngine`) pulls inside :meth:`step`. The
    ``serve.handoff_drop`` failpoint fires between pop and install — in
    the fleet that's a replica death with a popped item in hand (cleaned
    up by the death path); standalone, the held item is retried next
    step."""

    role = "DECODE"

    def __init__(self, cfg, params, serving=None, *, shared: SharedPagedState,
                 handoff: BlockHandoff, auto_pull: bool = True, **kw):
        super().__init__(cfg, params, serving=serving, shared=shared, **kw)
        self.handoff = handoff
        self._auto_pull = auto_pull
        self._holding: Optional[HandoffItem] = None   # popped, not installed

    @property
    def idle(self) -> bool:
        return (self.active == 0 and self._holding is None
                and (not self._auto_pull or self.handoff.pending == 0))

    @property
    def has_work(self) -> bool:
        return bool(self.active or self._holding is not None)

    @property
    def wants_dispatch(self) -> bool:
        return False                      # fed by the handoff, not submit

    @property
    def lanes_free(self) -> bool:
        return self._free_slot() is not None

    def install_item(self, item: HandoffItem) -> bool:
        """Install a popped item into a free lane (fleet dispatch path —
        caller holds the replica lock; we take the engine lock so a
        concurrent death-path collection can't interleave)."""
        with self._lock:
            return self._install_locked(item)

    def _install_locked(self, item: HandoffItem) -> bool:
        slot = self._free_slot()
        if slot is None:
            return False
        item.req.state = RUNNING
        self._slots[slot] = _Seq(item.req, item.blocks, item.table,
                                 item.ctx, item.last_tok)
        return True

    def _pull_handoff(self) -> None:
        # standalone path (caller holds self._lock): a previously-held
        # item (serve.handoff_drop escape) installs first
        if self._holding is not None:
            if not self._install_locked(self._holding):
                return
            self._holding = None
        while self._free_slot() is not None:
            item = self.handoff.pop()
            if item is None:
                return
            self._holding = item
            chaos.failpoint("serve.handoff_drop")
            self._install_locked(item)
            self._holding = None

    def step(self) -> int:
        with self._lock:
            if self._auto_pull:
                self.handoff.shed_expired()
                self._pull_handoff()
            done = self._decode_step() if self.active else 0
            self.steps += 1
            self._stamp_heartbeat()
            return done

    def warm(self) -> None:
        """Compile the decode step off the serving path: all-null-table
        decodes (writes sink into the null block, outputs are discarded)
        — a restarted decode replica must not pay its XLA compile under
        a live heartbeat timeout. Runs TWICE so both the fresh-pools and
        the donated-committed-pools specializations are compiled (see
        PrefillEngine.warm)."""
        import jax
        import jax.numpy as jnp
        from .kv_cache import NULL_BLOCK
        with self._lock:
            B = self.max_batch
            for _ in range(2):
                self._rng, r = jax.random.split(self._rng)
                self._run_device(
                    self._decode_fn, jnp.zeros((B,), jnp.int32),
                    jnp.full((B, self.nbk), NULL_BLOCK, jnp.int32),
                    jnp.zeros((B,), jnp.int32), r,
                    jnp.zeros((B,), jnp.float32),
                    jnp.zeros((B,), jnp.int32),
                    jnp.ones((B,), jnp.float32))

    def _collect_held(self, blocks, reqs) -> None:
        if self._holding is not None:
            blocks.append(self._holding.blocks)
            reqs.append(self._holding.req)
            self._holding = None


class DisaggEngine:
    """The single-process disaggregated pair (tests, batch use, and the
    API the fleet mirrors): one PrefillEngine + one DecodeEngine over one
    shared paged state and one handoff queue, stepped together. Greedy
    output is token-exact with whole-prefill serving and with sequential
    ``generate()`` (the acceptance matrix pins all three modes)."""

    def __init__(self, cfg, params, serving=None, heartbeat=None,
                 interpret: bool = False):
        from ..config.config import ServingConfig
        if serving is None:
            serving = ServingConfig()
        elif isinstance(serving, dict):
            serving = ServingConfig(**serving)
        self.scfg = serving
        self.shared = SharedPagedState(cfg, serving,
                                       dtype=resolve_kv_dtype(serving))
        self.handoff = BlockHandoff(self.shared.pool,
                                    capacity=serving.handoff_queue)
        self.prefill = PrefillEngine(cfg, params, serving=serving,
                                     shared=self.shared,
                                     handoff=self.handoff,
                                     heartbeat=heartbeat,
                                     interpret=interpret)
        self.decode = DecodeEngine(cfg, params, serving=serving,
                                   shared=self.shared, handoff=self.handoff,
                                   interpret=interpret)

    # ------------------------------------------------------------------ facade

    @property
    def pool(self):
        return self.shared.pool

    @property
    def pools(self):
        return self.shared.pools

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               **kw) -> Request:
        return self.prefill.submit(prompt, max_new_tokens, **kw)

    @property
    def idle(self) -> bool:
        return (self.prefill.idle and self.decode.idle
                and self.handoff.pending == 0)

    def step(self) -> int:
        """One disagg iteration: at most one prefill chunk, then one
        decode step — the two roles' device work serializes on the
        shared pool's lock (one process, one device); the fleet runs the
        same pair on worker threads."""
        done = self.prefill.step()
        # drain the handed-off ledger (the fleet's bookkeeping hook) so
        # long-lived standalone use doesn't accumulate dead Requests
        self.prefill.take_handed_off()
        done += self.decode.step()
        return done

    def run_until_idle(self, max_steps: int = 100_000) -> None:
        for _ in range(max_steps):
            if self.idle:
                return
            self.step()
        raise RuntimeError(f"disagg loop not idle after {max_steps} steps")

    def generate_batch(self, prompts: Sequence[Sequence[int]],
                       max_new_tokens: int = 32, temperature: float = 0.0,
                       eos_token_id=None) -> List[List[int]]:
        reqs = [self.submit(p, max_new_tokens, temperature=temperature,
                            eos_token_id=eos_token_id) for p in prompts]
        self.run_until_idle()
        return [r.output_tokens for r in reqs]

    def close(self) -> None:
        self.handoff.drain_release()
        self.prefill.close()
        self.decode.close()

    def __enter__(self) -> "DisaggEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def stats(self):
        """Merged role stats (prefill owns admission/prefill counters,
        decode owns completion counters; 'completed' sums both — a
        one-token request concludes on the prefill side). 'timeout'
        additionally counts handoff-queue sheds, which neither role's
        scheduler sees."""
        merged = dict(self.prefill.stats)
        for k, v in self.decode.stats.items():
            merged[k] = merged.get(k, 0) + v
        merged["timeout"] = merged.get("timeout", 0) + self.handoff.timed_out
        return merged
