"""ProcessFleet — the serving fleet with process-per-replica placement.

``serving.fleet.placement: "process"`` (round 18) runs each replica
engine in its own supervised OS process (serving/replica_worker.py)
instead of a thread: the failure domain the round-11 fleet shrank to a
thread becomes a real process boundary — a replica death is a process
death, its pool and compiled programs die WITH it (no abandoned-thread
leak), and the same machinery extends to replicas on other hosts. The
public surface mirrors :class:`~deepspeed_tpu.serving.fleet.ServingFleet`
(submit/drain/close/warmup/stats/deaths), so callers and the bench swap
placements without code changes; :func:`make_fleet` picks by config.

Plumbing — deliberately the MPMD supervisor's shape, over the round-18
transfer fabric (runtime/fabric/):

* **Weights via checkpoint load.** The hub saves params once
  (runtime/checkpointing.save_tree flat-npz) plus the model/serving
  configs as JSON into a workdir; every spawn (and every warmed
  restart) loads from there. No live arrays cross the fork.
* **A TCP star.** Workers dial in with hello ``{"ident": "replica-N"}``;
  the hub bumps that ident's EPOCH, answers ``welcome {gen: epoch}``
  (fabric generation fencing), and reads frames on a per-connection
  thread. A frame whose connection epoch is no longer current is
  dropped — a half-dead worker's late tokens cannot land after its
  requests were requeued. Link loss is NOT death: the worker redials
  (bounded fabric ladder) into a fresh epoch and keeps serving.
* **Exactly-once by hub arithmetic.** Dispatch sends ``prompt`` +
  ``emitted`` (the requeue prefix) and the budget; workers frame
  CUMULATIVE token lists with the dispatch ``base``, and the hub
  appends only ``toks[have - base:]`` — duplicated, reordered-by-
  redial, or replayed frames are no-ops on the FleetRequest ledger.
* **Death verdicts: process exit or heartbeat silence.** Workers stamp
  SERVE records (queue/active/pool_used/pid gauges) into the shared
  heartbeat dir (``dstpu health`` shows per-process replica rows); the
  supervisor poll declares DOWN only on ``proc.poll() is not None`` or
  ``heartbeat_timeout`` of record silence — the PR-6 contract. Teardown
  requeues in-flight requests token-exactly (retry budget, orphan
  parking on ``serve.requeue`` crashes), stamps STALLED evidence,
  strikes/blacklists/paroles, and respawns a warmed replacement with a
  fresh generation.

Disagg roles are refused: prefill/decode share ONE in-process pool by
construction — the zero-copy handoff cannot cross a process boundary.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..runtime import heartbeat as hb
from ..runtime.fabric import HubConn, read_frame
from ..testing import chaos
from ..utils.logging import log_dist, logger
from .autoscale import (AUTOSCALER_RANK, SCALE_DOWN, SCALE_UP,
                        AutoscalePolicy, Observation, ScaleEvent)
from .fleet import BLACKLISTED, DOWN, LIVE, RETIRED, FleetRequest
from .scheduler import (BATCH, FAILED, FINISHED, LATENCY, PRIORITY_TIERS,
                        QUEUED, RUNNING, SHED, STANDARD, TIER_RANK, TIMEOUT,
                        TieredQueue, admit_or_shed, check_admissible)

PyTree = Any


class _Proc:
    """One replica process slot. A restart builds a NEW _Proc for the
    same index (strikes carried) — the dead one keeps its Popen handle
    for post-mortem rc reads only."""

    def __init__(self, idx: int, generation: int = 0, strikes: int = 0):
        self.idx = idx
        self.generation = generation   # spawn generation (death ledger)
        self.strikes = strikes
        self.state = LIVE
        self.ready = False             # worker warmed + said hello
        self.draining = False          # scale-down in flight (round 19)
        self.proc: Optional[subprocess.Popen] = None
        self.conn: Optional[HubConn] = None
        self.pid: Optional[int] = None
        self.inflight: Dict[int, FleetRequest] = {}
        self.error: Optional[str] = None
        self.started_ts = time.monotonic()
        self.retired_ts: Optional[float] = None


class ProcessFleet:
    """See module docstring. Same constructor shape as ServingFleet;
    ``workdir`` overrides the private tempdir the weights npz + config
    JSONs land in; ``env_first`` is overlaid on the FIRST spawn of each
    replica only (StageWorkerSpec semantics — one-shot DSTPU_CHAOS
    specs must not re-arm in restarted processes)."""

    def __init__(self, cfg, params: PyTree, serving=None,
                 heartbeat_dir: Optional[str] = None,
                 workdir: Optional[str] = None,
                 env_first: Optional[Dict[str, str]] = None,
                 log_dir: Optional[str] = None):
        from ..config.config import ServingConfig
        if serving is None:
            serving = ServingConfig()
        elif isinstance(serving, dict):
            serving = ServingConfig(**serving)
        self.cfg = cfg
        self.scfg = serving
        self.fcfg = serving.fleet
        if int(self.fcfg.prefill_replicas) or int(self.fcfg.decode_replicas):
            raise ValueError(
                "serving.fleet: placement='process' requires plain "
                "replicas — disaggregated prefill/decode roles share one "
                "in-process KV pool (the zero-copy handoff cannot cross "
                "a process boundary)")
        self.n_replicas = max(1, int(self.fcfg.replicas))
        # traffic-shaped autoscaling (round 19): the SAME policy the
        # thread fleet feeds — disagg is already refused above, so the
        # plain-replicas precondition holds by construction
        self.autoscale: Optional[AutoscalePolicy] = None
        if self.fcfg.autoscale.enabled:
            self.autoscale = AutoscalePolicy(self.fcfg.autoscale)
            self.n_replicas = min(max(self.n_replicas,
                                      self.autoscale.min_replicas),
                                  self.autoscale.max_replicas)
        self.heartbeat_dir = (heartbeat_dir or self.fcfg.heartbeat_dir
                              or tempfile.mkdtemp(prefix="dstpu-pfleet-hb-"))
        self.workdir = workdir or tempfile.mkdtemp(prefix="dstpu-pfleet-")
        self.log_dir = log_dir
        self._env_first = dict(env_first or {})
        self._env_first_spawned: set = set()
        self._queue = TieredQueue(                # guarded by _qlock
            aging_s=float(self.fcfg.priority_aging_s))
        self._qlock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._orphans: List[FleetRequest] = []
        self._outstanding: Dict[int, FleetRequest] = {}
        self._rid = 0
        self._stop = threading.Event()
        self._started = False
        self._lock = threading.Lock()            # replica-list mutations
        self._replicas: List[_Proc] = [_Proc(i)
                                       for i in range(self.n_replicas)]
        #: per-ident hello epoch — the fabric generation fence. Bumped on
        #: every hello AND on every death verdict, so frames from a
        #: fenced connection can never land post-requeue.
        self._epochs: List[int] = [0] * self.n_replicas
        self._server: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self._accept_t: Optional[threading.Thread] = None
        self._poll_t: Optional[threading.Thread] = None
        self._logs: Dict[int, Any] = {}
        self.deaths: List[dict] = []
        #: capacity ledger (round 19), mirroring ServingFleet: every
        #: autoscaler verdict with its trigger and queue/live evidence
        self.scale_events: List[ScaleEvent] = []
        self._as_writer: Optional[hb.HeartbeatWriter] = None
        self.stats: Dict[str, int] = {
            "submitted": 0, "completed": 0, "failed": 0, "timeout": 0,
            "requeues": 0, "deaths": 0, "restarts": 0, "paroles": 0,
            "blacklisted": 0, "tokens_emitted": 0, "shed": 0,
            "preempted": 0, "scale_ups": 0, "scale_downs": 0}
        hb.clear_channel(self.heartbeat_dir)
        self._stage_artifacts(params)
        log_dist(
            f"ProcessFleet: {self.n_replicas} replica processes, "
            f"retry_budget={self.fcfg.retry_budget}, "
            f"heartbeat_dir={self.heartbeat_dir}", ranks=[0])

    # ------------------------------------------------------------------ setup

    def _stage_artifacts(self, params: PyTree) -> None:
        """Write the restart-stable artifacts every spawn loads: weights
        as a flat npz, model + serving configs as JSON."""
        from ..runtime.checkpointing import save_tree
        from .replica_worker import cfg_to_dict
        os.makedirs(self.workdir, exist_ok=True)
        self._params_path = os.path.join(self.workdir, "params.npz")
        save_tree(params, self._params_path)
        self._model_json = os.path.join(self.workdir, "model.json")
        with open(self._model_json, "w") as f:
            json.dump(cfg_to_dict(self.cfg), f)
        self._serving_json = os.path.join(self.workdir, "serving.json")
        with open(self._serving_json, "w") as f:
            json.dump(self.scfg.model_dump(mode="json"), f)

    def _worker_cmd(self, idx: int) -> List[str]:
        argv = ["--replica", str(idx),
                "--hub-port", str(self.port),
                "--params", self._params_path,
                "--model-json", self._model_json,
                "--serving-json", self._serving_json,
                "--hb-dir", self.heartbeat_dir,
                "--hb-interval", str(self.fcfg.heartbeat_interval)]
        # sys.path INSIDE the child, never PYTHONPATH (the MPMD driver's
        # bootstrap: an inherited PYTHONPATH shadows TPU-plugin deps)
        import deepspeed_tpu
        pkg_root = os.path.dirname(os.path.dirname(deepspeed_tpu.__file__))
        boot = ("import sys; sys.path.insert(0, {root!r}); "
                "from deepspeed_tpu.serving.replica_worker "
                "import main; raise SystemExit(main({argv!r}))").format(
                    root=pkg_root, argv=argv)
        return [sys.executable, "-c", boot]

    def _spawn(self, rep: _Proc) -> None:
        env = dict(os.environ)
        if rep.idx not in self._env_first_spawned:
            env.update(self._env_first)
            self._env_first_spawned.add(rep.idx)
        out = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            if rep.idx not in self._logs:
                self._logs[rep.idx] = open(
                    os.path.join(self.log_dir,
                                 f"replica{rep.idx}.log"), "ab")
            out = self._logs[rep.idx]
        proc = subprocess.Popen(
            self._worker_cmd(rep.idx), env=env, stdout=out,
            stderr=subprocess.STDOUT if out else None)
        with self._lock:
            rep.proc = proc
            rep.pid = proc.pid

    # -------------------------------------------------------------- lifecycle

    def start(self) -> "ProcessFleet":
        if self._started:
            return self
        self._started = True
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", 0))
        self._server.listen(self.n_replicas + 4)
        self.port = self._server.getsockname()[1]
        self._accept_t = threading.Thread(target=self._accept_loop,
                                          daemon=True)
        self._accept_t.start()
        for rep in self._replicas:
            self._spawn(rep)
        if self.autoscale is not None:
            # the autoscaler's own heartbeat rank — scale events are
            # operator evidence in the SAME channel `dstpu health`
            # reads; refreshed every supervisor poll
            self._as_writer = hb.HeartbeatWriter(
                self.heartbeat_dir, rank=AUTOSCALER_RANK,
                host="autoscaler",
                min_interval=float(self.fcfg.heartbeat_interval),
                refresh_interval=0.0)
            self._stamp_autoscaler(force=True)
        self._poll_t = threading.Thread(target=self._poll_loop, daemon=True)
        self._poll_t.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        """Stop polling, ask workers to exit (rc 0), reap within
        ``timeout``, kill stragglers. Outstanding requests are left
        un-concluded — drain first if they matter."""
        self._stop.set()
        deadline = time.monotonic() + timeout
        for rep in self._replicas:
            conn = rep.conn
            if conn is not None:
                try:
                    conn.send({"cmd": "stop"})
                except OSError:
                    pass
        for rep in self._replicas:
            p = rep.proc
            if p is None or p.poll() is not None:
                continue
            try:
                p.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(5.0)
        for rep in self._replicas:
            if rep.conn is not None:
                rep.conn.close()
                rep.conn = None
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        if self._poll_t is not None:
            self._poll_t.join(2.0)
        if self._as_writer is not None:
            self._as_writer.stamp_terminal(hb.PHASE_EXIT, lock_timeout=1.0)
        for f in self._logs.values():
            try:
                f.close()
            except OSError:
                pass

    def __enter__(self) -> "ProcessFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------- submission
    # (the ServingFleet contract verbatim — same admission predicate,
    # same bounded queue, same failpoint)

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               temperature: float = 0.0, eos_token_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               on_token=None, on_finish=None,
               priority: str = STANDARD) -> FleetRequest:
        chaos.failpoint("serve.enqueue")
        if priority not in TIER_RANK:
            raise ValueError(f"unknown priority tier {priority!r}; pick "
                             f"one of {PRIORITY_TIERS}")
        prompt = [int(t) for t in prompt]
        bs = int(self.scfg.block_size)
        check_admissible(
            len(prompt), int(max_new_tokens), bs,
            int(self.scfg.pool_blocks),
            min(int(self.scfg.max_blocks_per_seq) * bs,
                self.cfg.max_seq_len))
        if deadline_s is None and self.fcfg.default_deadline_s > 0:
            deadline_s = self.fcfg.default_deadline_s
        with self._qlock:
            self._rid += 1
            req = FleetRequest(
                prompt=prompt, max_new_tokens=int(max_new_tokens),
                temperature=float(temperature), eos_token_id=eos_token_id,
                on_token=on_token, on_finish=on_finish, rid=self._rid,
                priority=priority)
            if deadline_s is not None:
                req.deadline_ts = req.arrival_ts + float(deadline_s)
            # the round-19 overload ladder (scheduler.admit_or_shed):
            # raises AdmissionRejected before touching fleet state
            victim = admit_or_shed(self._queue, req,
                                   int(self.fcfg.max_queue),
                                   float(self.fcfg.batch_highwater))
            self._outstanding[req.rid] = req
        self._bump("submitted")
        if victim is not None:
            self._conclude(victim, SHED, json.dumps(
                {"error": "shed", "reason": "displaced_by_tier",
                 "tier": victim.priority}, sort_keys=True))
        return req

    @property
    def pending(self) -> int:
        with self._qlock:
            return len(self._queue) + len(self._orphans)

    @property
    def idle(self) -> bool:
        with self._qlock:
            return not self._outstanding

    def live_replicas(self) -> List[int]:
        with self._lock:
            return [r.idx for r in self._replicas if r.state == LIVE]

    def pids(self) -> Dict[int, Optional[int]]:
        """Live replica index -> worker PID (the chaos matrix and the
        bench kill PROCESSES, not threads)."""
        with self._lock:
            return {r.idx: r.pid for r in self._replicas
                    if r.state == LIVE}

    def drain(self, timeout: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._qlock:
                reqs = list(self._outstanding.values())
            if not reqs:
                return True
            reqs[0].wait(min(0.05, max(deadline - time.monotonic(), 0.0)))
            with self._qlock:
                for rid in [r.rid for r in reqs if r.done]:
                    self._outstanding.pop(rid, None)
        with self._qlock:
            return not self._outstanding

    def warmup(self, prompt: Optional[Sequence[int]] = None,
               max_new_tokens: int = 2, timeout: float = 120.0) -> None:
        """Block until every live replica process compiled and said
        ready — workers warm THEMSELVES at spawn (weights + compile off
        the serving path); this is the barrier, not the trigger."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                waiting = [r.idx for r in self._replicas
                           if r.state == LIVE and not r.ready]
            if not waiting:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"fleet warmup: replicas {waiting} not ready in {timeout}s")

    # ------------------------------------------------------------- hub plumbing

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._server.settimeout(0.2)
                sock, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(sock,),
                             daemon=True).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        """Hello -> epoch bump -> welcome -> reader loop. Re-dials from
        a living worker land here too: the NEW epoch fences every frame
        the old connection might still cough up."""
        try:
            meta, _ = read_frame(sock)
            if meta.get("cmd") != "hello":
                sock.close()
                return
            idx = int(meta["replica"])
            with self._lock:
                if not 0 <= idx < self.n_replicas:
                    sock.close()
                    return
                self._epochs[idx] += 1
                epoch = self._epochs[idx]
                rep = self._replicas[idx]
                dead = rep.state != LIVE
                old = rep.conn if not dead else None
            if dead:
                # a RETIRED (or verdicted) worker redialing in: answer
                # with the stop its teardown may have missed — the epoch
                # bump above already fences anything it frames meanwhile
                conn = HubConn(sock, ident=f"replica-{idx}", gen=epoch)
                conn.welcome()
                try:
                    conn.send({"cmd": "stop"})
                except OSError:
                    pass
                conn.close()
                return
            with self._lock:
                conn = HubConn(sock, ident=f"replica-{idx}", gen=epoch)
                rep.conn = conn
                if meta.get("pid") is not None:
                    rep.pid = int(meta["pid"])
            if old is not None:
                old.close()
            conn.welcome()
            # re-dispatch everything this replica still owes: a redial
            # means frames in flight on the old connection may be LOST
            # (a serve command the worker never read would strand its
            # request RUNNING forever). The worker dedups by rid, and
            # the emitted prefix + base arithmetic keep a genuinely
            # re-served request token-exact — so re-sending is free.
            with self._qlock:
                owed = [(req, list(req.output_tokens))
                        for req in rep.inflight.values() if not req.done]
            for req, emitted in owed:
                dl = (max(req.deadline_ts - time.monotonic(), 0.0)
                      if req.deadline_ts is not None else None)
                conn.send({"cmd": "serve", "rid": req.rid,
                           "prompt": req.prompt,
                           "max_new_tokens": req.max_new_tokens,
                           "emitted": emitted,
                           "temperature": req.temperature,
                           "eos": req.eos_token_id, "deadline_s": dl})
        except (OSError, ValueError, KeyError):
            try:
                sock.close()
            except OSError:
                pass
            return
        self._read_conn(rep, conn, epoch)

    def _read_conn(self, rep: _Proc, conn: HubConn, epoch: int) -> None:
        while not self._stop.is_set():
            try:
                meta, _ = read_frame(conn.sock)
            except OSError:
                break
            with self._lock:
                stale = self._epochs[rep.idx] != epoch
            if stale:
                break                   # fenced: drop frame, stop reading
            cmd = meta.get("cmd")
            if cmd == "ready":
                with self._lock:
                    rep.ready = True
            elif cmd in ("prog", "done"):
                self._apply_tokens(rep, meta, final=(cmd == "done"))
                if cmd == "done":
                    # at-least-once done delivery: the worker re-sends
                    # its conclusion until acked; _apply_tokens is
                    # idempotent, so a duplicate costs nothing and a
                    # frame lost to corruption/partition costs a retry
                    try:
                        conn.send({"cmd": "ack", "rid": int(meta["rid"])})
                    except OSError:
                        pass            # next re-send lands on the redial
        # the reader owns teardown of ITS connection: closing the socket
        # (not just dropping the ref) is what turns a one-sided hub-side
        # failure (e.g. a FrameCorrupt read) into the OSError the
        # worker's send path needs to trigger its redial ladder
        conn.close()
        with self._lock:
            if rep.conn is conn:
                rep.conn = None         # link lost — NOT death; the
                #                         worker redials, or the poll's
                #                         exit/silence verdict lands

    def _apply_tokens(self, rep: _Proc, meta: dict, final: bool) -> None:
        """The exactly-once append: cumulative leg tokens + dispatch
        base make every frame idempotent on the hub ledger."""
        rid = int(meta["rid"])
        base = int(meta.get("base", 0))
        toks = [int(t) for t in meta.get("toks", [])]
        fresh: List[int] = []
        with self._qlock:
            req = self._outstanding.get(rid)
            if req is None or req.done or req.replica != rep.idx:
                return                  # concluded or reassigned: stale
            have = len(req.output_tokens)
            fresh = toks[max(have - base, 0):]
            req.output_tokens.extend(fresh)
        if fresh:
            self._bump("tokens_emitted", len(fresh))
            if req.on_token is not None:
                for t in fresh:
                    try:
                        req.on_token(req, t)
                    except Exception:
                        logger.exception(
                            "fleet: on_token for request %d raised", rid)
        if final:
            rep.inflight.pop(rid, None)
            state = meta.get("state", FINISHED)
            if state not in (FINISHED, FAILED, TIMEOUT):
                state = FINISHED
            self._conclude(req, state, meta.get("error"))
        elif (len(req.output_tokens) >= req.max_new_tokens
              or (req.eos_token_id is not None and fresh
                  and fresh[-1] == req.eos_token_id)):
            # budget/eos satisfaction concludes hub-side even if the
            # worker's done frame is lost on the wire — the cumulative
            # prog that carried the last token is proof enough
            rep.inflight.pop(rid, None)
            self._conclude(req, FINISHED)

    # -------------------------------------------------------------- supervisor

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception:
                logger.exception("ProcessFleet: poll failed")
            self._stop.wait(float(self.fcfg.poll_interval))

    def poll(self) -> List[dict]:
        """One supervision pass (public for deterministic tests): death
        verdicts (process exit / heartbeat silence), orphan retries,
        deadline sheds, dispatch. Returns deaths verdicted this pass."""
        verdicts: List[dict] = []
        with self._lock:
            reps = list(self._replicas)
            ready = {r.idx for r in reps if r.ready}
        timeout = float(self.fcfg.heartbeat_timeout)
        records = hb.read_heartbeats(self.heartbeat_dir)
        # stale_ranks returns RECORDS (non-terminal, gone silent), not
        # rank ints — project down to the rank set before membership tests
        stale = ({int(rec["rank"]) for rec in hb.stale_ranks(
                      self.heartbeat_dir, timeout, records=records)}
                 if timeout > 0 else set())
        now = time.monotonic()
        for rep in reps:
            if rep.state == RETIRED and rep.proc is not None:
                # reap the retired worker (its stop command exits rc 0
                # and it stamps its own EXIT). A worker that never got
                # the stop — link down at drain time — is killed after a
                # grace window; the hub stamps EXIT on its behalf (a
                # RETIRED replica concluded, it did not fail).
                if rep.proc.poll() is None and rep.retired_ts is not None \
                        and now - rep.retired_ts > 5.0:
                    rep.proc.kill()
                    rep.retired_ts = None
                    try:
                        w = hb.HeartbeatWriter(
                            self.heartbeat_dir, rank=rep.idx,
                            refresh_interval=0)
                        w.stamp_terminal(hb.PHASE_EXIT, lock_timeout=1.0)
                    except Exception:
                        pass
                continue
            if rep.state != LIVE or rep.proc is None:
                continue
            rc = rep.proc.poll()
            if rc is not None and rc != 0:
                verdicts.append(self._replica_down(
                    rep, f"process exit rc={rc}", records.get(rep.idx)))
            elif rc == 0 and not self._stop.is_set():
                # a worker never exits 0 unbidden — treat as death too
                # (covers a stop command it was never sent)
                verdicts.append(self._replica_down(
                    rep, "process exit rc=0", records.get(rep.idx)))
            elif rep.idx in ready and rep.idx in stale:
                verdicts.append(self._replica_down(
                    rep, "heartbeat silence", records.get(rep.idx)))
        self._retry_orphans()
        self._shed_expired()
        self._maybe_parole()
        self._maybe_preempt()
        self._autoscale_tick()
        self._stamp_autoscaler()
        self._dispatch_all()
        return verdicts

    def _replica_down(self, rep: _Proc, reason: str,
                      evidence: Optional[dict]) -> dict:
        """Tear down ONE replica process: bump its epoch FIRST (fencing
        any frames a half-dead worker or dying connection still emits —
        the process-placement analogue of marking DOWN under the replica
        lock), kill the process, requeue in-flight token-exactly, stamp
        STALLED evidence, then strike / blacklist / warmed restart."""
        with self._lock:
            if rep.state != LIVE:
                return {}
            rep.state = DOWN
            self._epochs[rep.idx] += 1
            conn, rep.conn = rep.conn, None
            pid = rep.pid
        if conn is not None:
            conn.close()
        if rep.proc is not None and rep.proc.poll() is None:
            rep.proc.kill()
            try:
                rep.proc.wait(5.0)
            except subprocess.TimeoutExpired:
                pass
        inflight = list(rep.inflight.values())
        rep.inflight.clear()
        rep.strikes += 1
        self._bump("deaths")
        try:
            w = hb.HeartbeatWriter(self.heartbeat_dir, rank=rep.idx,
                                   refresh_interval=0)
            w.stamp_terminal(hb.PHASE_STALLED, lock_timeout=1.0)
        except Exception:
            pass                        # diagnostics must not block teardown
        death = {"replica": rep.idx, "generation": rep.generation,
                 "reason": reason, "error": rep.error, "evidence": evidence,
                 "strikes": rep.strikes, "detected_ts": time.monotonic(),
                 "action": None, "restarted_ts": None}
        self.deaths.append(death)
        logger.warning(
            "fleet: replica process %d DOWN (%s; strike %d; pid %s)",
            rep.idx, reason, rep.strikes, pid)
        for req in reversed(inflight):
            self._requeue(req)
        if rep.draining:
            # the replica was already being scaled down: its death just
            # ends the drain early — lanes requeued exactly-once above,
            # and the autoscaler wanted the capacity gone, so no strike
            # toward blacklist and no replacement
            rep.state = RETIRED
            death["action"] = "retired"
            self._note_drained(rep, clean=False)
            return death
        blacklist_after = int(self.fcfg.blacklist_after)
        if blacklist_after > 0 and rep.strikes >= blacklist_after:
            rep.state = BLACKLISTED
            with self._lock:
                self._replicas[rep.idx] = rep
            self._bump("blacklisted")
            death["action"] = "blacklist"
            logger.warning("fleet: replica %d BLACKLISTED after %d strikes",
                           rep.idx, rep.strikes)
            return death
        death["action"] = "restart"
        self._restart(rep.idx, rep.generation + 1, rep.strikes)
        death["restarted_ts"] = time.monotonic()
        return death

    def _requeue(self, req: FleetRequest, charge_retry: bool = True) -> None:
        """ServingFleet._requeue, minus the disagg arm: conclude spent /
        finished / expired requests, retry-budget the rest back onto the
        queue HEAD (of the request's own tier). A ``serve.requeue`` crash
        parks on the orphan list. ``charge_retry=False`` is the
        preemption path: the fleet evicted a healthy victim for capacity
        reasons, so the victim's failure budget is untouched."""
        try:
            chaos.failpoint("serve.requeue")
            if req.done:
                return
            if (req.remaining <= 0
                    or (req.eos_token_id is not None and req.output_tokens
                        and req.output_tokens[-1] == req.eos_token_id)):
                self._conclude(req, FINISHED)
                return
            if req.expired():
                self._conclude(req, TIMEOUT, "deadline exceeded at requeue")
                return
            if charge_retry:
                req.retries += 1
            if req.retries > int(self.fcfg.retry_budget):
                self._conclude(
                    req, FAILED,
                    f"retry budget exhausted ({self.fcfg.retry_budget} "
                    f"requeues) after replica failures")
                return
            req.replica, req.state = None, QUEUED
            with self._qlock:
                self._queue.appendleft(req)
            self._bump("requeues")
        except chaos.ChaosError as e:
            logger.warning("fleet: requeue of request %d failed (%s) — "
                           "orphaned for retry", req.rid, e)
            with self._qlock:
                self._orphans.append(req)

    def _retry_orphans(self) -> None:
        with self._qlock:
            orphans, self._orphans = self._orphans, []
        for req in orphans:
            self._requeue(req)

    def _shed_expired(self) -> None:
        now = time.monotonic()
        with self._qlock:
            expired = self._queue.remove_expired(now)
        for req in expired:
            self._conclude(req, TIMEOUT, "deadline exceeded while queued")

    def _restart(self, idx: int, generation: int, strikes: int,
                 parole: bool = False) -> None:
        fresh = _Proc(idx, generation=generation, strikes=strikes)
        with self._lock:
            self._replicas[idx] = fresh
        self._bump("restarts")
        if parole:
            self._bump("paroles")
        self._spawn(fresh)
        logger.warning("fleet: replica %d %s (process generation %d)",
                       idx, "PAROLED" if parole else "restarted", generation)

    def _maybe_parole(self) -> None:
        with self._lock:
            live = sum(1 for r in self._replicas if r.state == LIVE)
            if live >= max(1, int(self.fcfg.min_replicas)):
                return
            black = [r for r in self._replicas if r.state == BLACKLISTED]
        if not black:
            return
        rep = min(black, key=lambda r: r.strikes)
        self._restart(rep.idx, rep.generation + 1, rep.strikes, parole=True)

    # ------------------------------------------------- traffic shaping (round
    # 19: autoscaling + preemption — the process-placement mechanisms for
    # the one policy in serving/autoscale.py; mirrors ServingFleet)

    def _autoscale_tick(self) -> None:
        """Feed this poll's gauges through the AutoscalePolicy and
        perform its verdict; also completes any drain in flight. A
        spawned-but-not-ready worker counts as WARMING (it is compiling
        off-path), so the policy stays silent until it lands."""
        if self.autoscale is None:
            return
        now = time.monotonic()
        with self._lock:
            reps = list(self._replicas)
            serving = [r for r in reps if r.state == LIVE and r.ready
                       and not r.draining]
            warming = sum(1 for r in reps
                          if r.state == LIVE and not r.ready)
            draining = [r for r in reps if r.state == LIVE and r.draining]
        for rep in draining:
            self._finish_drain(rep)
        with self._qlock:
            qdepth = len(self._queue)
            pressured = self._queue.pressured(
                float(self.fcfg.autoscale.pressure_s), now)
        active = sum(len(r.inflight) for r in serving)
        obs = Observation(
            queue_depth=qdepth, pressured=pressured, live=len(serving),
            warming=warming, draining=len(draining), active_lanes=active,
            total_lanes=len(serving) * int(self.scfg.max_batch))
        verdict = self.autoscale.observe(obs, now)
        if verdict == SCALE_UP:
            self._scale_up(self.autoscale.describe(obs), obs)
        elif verdict == SCALE_DOWN:
            self._scale_down(self.autoscale.describe(obs), obs)

    def _scale_up(self, reason: str, obs: Observation) -> None:
        """Append a NEW replica slot — the replica list, the epoch fence
        table, and ``n_replicas`` (the hello-bound check) grow together
        under the list lock — and spawn its worker, which warms itself
        before saying ready (scaled-up capacity never serves cold). A
        ``serve.scale_up`` crash rolls the slot back and records
        ``up_failed``: a failed spawn leaves the fleet exactly as it
        was, and the policy's cooldown still debounces the retry."""
        with self._lock:
            idx = len(self._replicas)
            rep = _Proc(idx)
            self._replicas.append(rep)
            self._epochs.append(0)
            self.n_replicas += 1
        event = ScaleEvent(action=SCALE_UP, replica=idx, reason=reason,
                           ts=time.monotonic(), queue=obs.queue_depth,
                           live=obs.live)
        try:
            chaos.failpoint("serve.scale_up", key=str(idx))
            self._spawn(rep)
        except Exception as e:
            with self._lock:
                if self._replicas and self._replicas[-1] is rep:
                    self._replicas.pop()
                    self._epochs.pop()
                    self.n_replicas -= 1
            event.action = "up_failed"
            event.error = repr(e)
            self.scale_events.append(event)
            self._stamp_autoscaler(force=True)
            logger.warning("fleet: scale-up of replica process %d "
                           "failed: %s", idx, e)
            return
        self._bump("scale_ups")
        self.scale_events.append(event)
        self._stamp_autoscaler(force=True)
        logger.warning("fleet: scaled UP to replica process %d (%s)",
                       idx, reason)

    def _scale_down(self, reason: str, obs: Observation) -> None:
        """Start draining the NEWEST serving replica (LIFO keeps the
        original fleet's indices stable): dispatch skips it from now on,
        its in-flight lanes finish, and ``_finish_drain`` retires the
        process. The event is recorded at initiation (``drained_ts``
        lands at completion) so `dstpu health` shows the drain in
        flight."""
        with self._lock:
            cands = [r for r in self._replicas if r.state == LIVE
                     and r.ready and not r.draining]
        if len(cands) <= self.autoscale.min_replicas:
            return
        rep = max(cands, key=lambda r: r.idx)
        rep.draining = True
        self.scale_events.append(ScaleEvent(
            action=SCALE_DOWN, replica=rep.idx, reason=reason,
            ts=time.monotonic(), queue=obs.queue_depth, live=obs.live))
        self._stamp_autoscaler(force=True)
        logger.warning("fleet: scaling DOWN replica process %d (%s) — "
                       "draining", rep.idx, reason)

    def _finish_drain(self, rep: _Proc) -> None:
        """Retire a draining replica once its lanes emptied: flip to
        RETIRED *before* sending the stop command — the poll's
        process-exit check skips non-LIVE replicas, so the worker's
        clean rc-0 exit reads as the conclusion it is, not a death. The
        worker stamps its own EXIT terminal on the way out; the epoch
        bump fences any frame its dying connection still emits. A
        draining replica that DIES instead goes through
        ``_replica_down`` (exactly-once requeue, action 'retired')."""
        if rep.inflight:
            return
        with self._lock:
            if rep.state != LIVE or not rep.draining:
                return
            if rep.inflight:
                return
            rep.state = RETIRED
            rep.retired_ts = time.monotonic()
            self._epochs[rep.idx] += 1
            conn, rep.conn = rep.conn, None
        if conn is not None:
            try:
                conn.send({"cmd": "stop"})
            except OSError:
                pass                    # redial lands on the stop answer
            conn.close()
        self._note_drained(rep, clean=True)
        logger.warning("fleet: replica process %d RETIRED (drain "
                       "complete)", rep.idx)

    def _note_drained(self, rep: _Proc, clean: bool) -> None:
        """Conclude the replica's scale-down event in the capacity
        ledger (``clean=False``: the drain ended by death — its lanes
        requeued exactly-once rather than finishing in place)."""
        self._bump("scale_downs")
        for ev in reversed(self.scale_events):
            if ev.action == SCALE_DOWN and ev.replica == rep.idx \
                    and ev.drained_ts is None:
                ev.drained_ts = time.monotonic()
                if not clean:
                    ev.error = "drain ended by replica death"
                break
        self._stamp_autoscaler(force=True)

    def _stamp_autoscaler(self, force: bool = False) -> None:
        """The autoscaler's heartbeat record: refreshed every supervisor
        poll (never reads as silent while supervised), forced on every
        scale event — `dstpu health` shows the last verdict alongside
        the replica processes it acted on."""
        if self._as_writer is None:
            return
        try:
            with self._qlock:
                qdepth = len(self._queue)
            with self._lock:
                live = sum(1 for r in self._replicas
                           if r.state == LIVE and not r.draining)
            gauges = {"role": "AUTOSCALER", "queue": qdepth, "live": live,
                      "events": len(self.scale_events)}
            if self.scale_events:
                ev = self.scale_events[-1]
                gauges["event"] = f"{ev.action}@r{ev.replica}"
            self._as_writer.write(hb.PHASE_SERVE, len(self.scale_events),
                                  force=force, extra=gauges)
        except Exception:
            pass                        # diagnostics must not kill a poll

    def _maybe_preempt(self) -> None:
        """Deadline-pressured latency admission, process placement: when
        a latency-tier request is queued within ``preempt_pressure_s``
        of its deadline and no serving replica has a free lane, tell the
        youngest batch-tier victim's worker to ``cancel`` the lane and
        requeue the victim hub-side. Exactly-once holds by the existing
        ledger arithmetic: every prog frame already synced the emitted
        prefix cumulatively, frames the dying leg still sends before the
        cancel lands only extend that prefix idempotently, and once the
        victim is requeued (``replica = None``) the stale-frame guard in
        ``_apply_tokens`` drops anything late. ``serve.preempt`` fires
        between the lane eviction and the requeue: a crash there parks
        the victim on the orphan list — deferred, never lost. At most
        one eviction per poll bounds the churn."""
        window = float(self.fcfg.preempt_pressure_s)
        if window <= 0:
            return
        now = time.monotonic()
        with self._qlock:
            pressured = next(
                (r for r in self._queue
                 if r.priority == LATENCY and r.deadline_ts is not None
                 and 0.0 <= (r.deadline_ts - now) < window), None)
        if pressured is None:
            return
        cap = int(self.scfg.max_batch)
        with self._lock:
            reps = [r for r in self._replicas
                    if r.state == LIVE and r.ready and not r.draining
                    and r.conn is not None]
        if any(len(r.inflight) < cap for r in reps):
            return                       # a free lane will serve it
        victim_rep, victim = None, None
        for rep in reps:
            for req in rep.inflight.values():
                if req.priority == BATCH and not req.done \
                        and (victim is None
                             or req.arrival_ts > victim.arrival_ts):
                    victim_rep, victim = rep, req
        if victim is None:
            return
        with self._lock:
            conn = victim_rep.conn
        try:
            if conn is None:
                raise OSError("no connection")
            conn.send({"cmd": "cancel", "rid": victim.rid})
        except OSError:
            return                       # link down: the poll verdict owns it
        victim_rep.inflight.pop(victim.rid, None)
        victim.preemptions += 1
        self._bump("preempted")
        logger.warning(
            "fleet: preempting batch request %d on replica process %d "
            "for pressured latency request %d", victim.rid,
            victim_rep.idx, pressured.rid)
        try:
            chaos.failpoint("serve.preempt")
        except chaos.ChaosError as e:
            logger.warning(
                "fleet: preemption requeue of request %d failed (%s) — "
                "orphaned for retry", victim.rid, e)
            with self._qlock:
                self._orphans.append(victim)
            return
        self._requeue(victim, charge_retry=False)

    # --------------------------------------------------------------- dispatch

    def _dispatch_all(self) -> None:
        with self._lock:
            reps = [r for r in self._replicas
                    if r.state == LIVE and r.ready and not r.draining
                    and r.conn is not None]
        cap = int(self.scfg.max_batch)
        for rep in reps:
            while len(rep.inflight) < cap:
                with self._qlock:
                    req = self._queue.popnext()
                if req is None:
                    break
                if req.done:
                    continue
                if req.expired():
                    self._conclude(req, TIMEOUT,
                                   "deadline exceeded while queued")
                    continue
                dl = (max(req.deadline_ts - time.monotonic(), 0.0)
                      if req.deadline_ts is not None else None)
                frame = {"cmd": "serve", "rid": req.rid,
                         "prompt": req.prompt,
                         "max_new_tokens": req.max_new_tokens,
                         "emitted": list(req.output_tokens),
                         "temperature": req.temperature,
                         "eos": req.eos_token_id, "deadline_s": dl}
                with self._lock:
                    conn = rep.conn
                try:
                    if conn is None:
                        raise OSError("no connection")
                    conn.send(frame)
                except OSError:
                    # never delivered: back on the HEAD, not a retry.
                    # The link is down — the worker redials or the next
                    # poll's verdict lands; either way stop pushing.
                    with self._qlock:
                        self._queue.appendleft(req)
                    with self._lock:
                        if rep.conn is conn:
                            rep.conn = None
                    break
                req.replica, req.state = rep.idx, RUNNING
                rep.inflight[req.rid] = req

    # ------------------------------------------------------------------ misc

    def _conclude(self, req: FleetRequest, state: str,
                  error: Optional[str] = None) -> None:
        if not req._finish(state, error):
            return
        with self._qlock:
            self._outstanding.pop(req.rid, None)
        self._bump({FINISHED: "completed", FAILED: "failed",
                    TIMEOUT: "timeout", SHED: "shed"}[state])

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] = self.stats.get(key, 0) + n


def make_fleet(cfg, params: PyTree, serving=None, **kw):
    """Placement-dispatching fleet constructor: ``serving.fleet.
    placement`` picks :class:`~deepspeed_tpu.serving.fleet.ServingFleet`
    (threads, the default) or :class:`ProcessFleet` (supervised OS
    processes). Both expose the same serving surface."""
    from ..config.config import ServingConfig
    from .fleet import ServingFleet
    if serving is None:
        serving = ServingConfig()
    elif isinstance(serving, dict):
        serving = ServingConfig(**serving)
    placement = str(serving.fleet.placement)
    if placement == "process":
        kw.pop("interpret", None)       # in-process knob; workers compile
        return ProcessFleet(cfg, params, serving=serving, **kw)
    if placement != "thread":
        raise ValueError(
            f"serving.fleet.placement {placement!r}: expected 'thread' "
            "or 'process'")
    return ServingFleet(cfg, params, serving=serving, **kw)
