"""Production serving: continuous batching over a paged KV cache.

- :mod:`~deepspeed_tpu.serving.kv_cache` — block pool, refcounted
  fork/free, prefix cache (vLLM-style paged layout);
- :mod:`~deepspeed_tpu.serving.model_runner` — paged transformer
  forward (generation-path numerics, block-table K/V);
- :mod:`~deepspeed_tpu.serving.scheduler` — FIFO admission control under
  the block budget;
- :mod:`~deepspeed_tpu.serving.engine` — the fixed-shape serving loop
  (one decode-step compile, SERVE heartbeat phase);
- :mod:`~deepspeed_tpu.serving.fleet` — supervised multi-replica fleet
  (shared admission queue, heartbeat-driven replica death detection,
  exactly-once request requeue, blacklist/parole, graceful degradation).

Entry points: ``ServingEngine(cfg, params, serving_config)`` directly, or
``deepspeed_tpu.init_inference(...).serve()`` (which returns a started
``ServingFleet`` when ``serving.fleet.replicas > 1``).
"""

from .engine import ServingEngine
from .fleet import FleetRequest, FleetSupervisor, ServingFleet
from .kv_cache import BlockPool, BlockPoolExhausted, PrefixCache, init_pool
from .scheduler import Request, Scheduler

__all__ = ["ServingEngine", "ServingFleet", "FleetSupervisor",
           "FleetRequest", "BlockPool", "BlockPoolExhausted", "PrefixCache",
           "init_pool", "Request", "Scheduler"]
