"""Production serving: continuous batching over a paged KV cache.

- :mod:`~deepspeed_tpu.serving.kv_cache` — block pool, refcounted
  fork/free, prefix cache (vLLM-style paged layout);
- :mod:`~deepspeed_tpu.serving.model_runner` — paged transformer
  forward (generation-path numerics, block-table K/V);
- :mod:`~deepspeed_tpu.serving.scheduler` — FIFO admission control under
  the block budget;
- :mod:`~deepspeed_tpu.serving.engine` — the fixed-shape serving loop
  (one decode-step compile, SERVE heartbeat phase);
- :mod:`~deepspeed_tpu.serving.fleet` — supervised multi-replica fleet
  (shared admission queue, heartbeat-driven replica death detection,
  exactly-once request requeue, blacklist/parole, graceful degradation);
- :mod:`~deepspeed_tpu.serving.disagg` — disaggregated serving (round
  12): PrefillEngine/DecodeEngine roles over a bounded paged-KV block
  handoff, zero-copy via the shared refcounted pool;
- :mod:`~deepspeed_tpu.serving.procfleet` — process-per-replica
  placement (round 18): each replica engine in a supervised OS process
  (serving/replica_worker.py), request/token streams over the transfer
  fabric (runtime/fabric/), the same fleet surface — pick by
  ``serving.fleet.placement`` via :func:`make_fleet`.

Entry points: ``ServingEngine(cfg, params, serving_config)`` directly,
``DisaggEngine`` for the single-process disagg pair, or
``deepspeed_tpu.init_inference(...).serve()`` (which returns a started
``ServingFleet`` when ``serving.fleet.replicas > 1`` or both
``fleet.prefill_replicas``/``decode_replicas`` are set).
"""

from .disagg import (BlockHandoff, DecodeEngine, DisaggEngine, HandoffItem,
                     PrefillEngine)
from .engine import ServingEngine, lane_topk_topp
from .fleet import FleetRequest, FleetSupervisor, ServingFleet
from .kv_cache import (BlockPool, BlockPoolExhausted, PrefixCache,
                       SharedPagedState, init_pool)
from .procfleet import ProcessFleet, make_fleet
from .scheduler import Request, Scheduler

__all__ = ["ServingEngine", "ServingFleet", "FleetSupervisor",
           "FleetRequest", "BlockPool", "BlockPoolExhausted", "PrefixCache",
           "SharedPagedState", "init_pool", "Request", "Scheduler",
           "DisaggEngine", "PrefillEngine", "DecodeEngine", "BlockHandoff",
           "HandoffItem", "lane_topk_topp", "ProcessFleet", "make_fleet"]
