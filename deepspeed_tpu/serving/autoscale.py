"""Traffic-shaped replica autoscaling: the policy half (round 19).

The fleet already reacts to *faults* through supervised evidence — a
dead thread, heartbeat silence, a straggler verdict. This module applies
the same evidence-driven discipline to *load*: every supervisor poll
feeds an :class:`Observation` (the fleet's OWN SERVE heartbeat gauges —
queue depth, active lanes, live/warming replica counts, deadline
pressure) into an :class:`AutoscalePolicy`, which answers ``"up"``,
``"down"``, or ``None``.

Design contract (docs/SERVING.md §Autoscaling):

* **Deterministic and clock-injectable.** The policy holds no threads
  and does no I/O: ``observe(obs, now)`` is a pure state machine over
  explicit timestamps, so the false-flap guards are unit tests with a
  fake clock, not sleeps.
* **Hysteresis + cooldown, both directions.** A scale-up needs
  ``up_after`` CONSECUTIVE overloaded observations; a scale-down needs
  an unbroken ``down_idle_s`` seconds of idle trough. After ANY verdict
  ``cooldown_s`` must pass before the next — a single burst causes at
  most one event (pinned test).
* **Warming is not idleness.** While any replica is warming (compiling
  off-path before taking traffic) the policy issues NO verdict at all:
  the warming replica is capacity already in flight (scaling up again
  would overshoot) and its heartbeat silence is compile, not an idle
  fleet (scaling down would flap). Pinned test.
* **Bounds.** ``min_replicas <= live <= max_replicas`` — the parole
  floor and the chip budget. The DECISION is bounded here; the
  MECHANISM (warmed spawn / drain-then-teardown) lives in the fleets.

The mechanism half — spawning a warmed replica, draining one through
the straggler-drain path, stamping scale events into the heartbeat
channel — lives in serving/fleet.py and serving/procfleet.py; both feed
this one policy.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

#: reserved heartbeat rank for the autoscaler's own record: scale events
#: are operator evidence, so they land in the SAME channel `dstpu
#: health` reads — far above any replica index (replicas grow from 0,
#: bounded by max_replicas).
AUTOSCALER_RANK = 999

SCALE_UP, SCALE_DOWN = "up", "down"


@dataclass
class Observation:
    """One supervisor poll's view of the fleet, in gauge terms (the same
    numbers the replicas stamp into their SERVE heartbeats)."""
    queue_depth: int = 0       # shared admission queue length
    pressured: int = 0         # queued requests near their deadline
    live: int = 0              # LIVE replicas taking traffic (not draining)
    warming: int = 0           # replicas spawned but still compiling
    draining: int = 0          # replicas winding down (scale-down in flight)
    active_lanes: int = 0      # busy decode lanes across live replicas
    total_lanes: int = 0       # capacity: live replicas x max_batch


@dataclass
class ScaleEvent:
    """One autoscaling verdict, as recorded in ``fleet.scale_events``
    and stamped into the heartbeat channel (the death-ledger idiom,
    applied to capacity)."""
    action: str                # "up" | "down" | "up_failed"
    replica: int               # replica index spawned / drained (-1: none)
    reason: str                # trigger, human- and machine-readable
    ts: float                  # monotonic timestamp of the verdict
    queue: int                 # queue depth at the verdict
    live: int                  # live replica count at the verdict
    drained_ts: Optional[float] = None   # scale-down: drain completion
    error: Optional[str] = None          # up_failed / drain-by-death detail

    def as_gauges(self) -> dict:
        return {"event": f"{self.action}@r{self.replica}",
                "reason": self.reason, "queue": self.queue,
                "live": self.live}


class AutoscalePolicy:
    """Queue-depth + deadline-pressure triggers behind hysteresis,
    cooldown, and min/max bounds. One instance per fleet; the supervisor
    calls :meth:`observe` once per poll."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.min_replicas = max(1, int(cfg.min_replicas))
        self.max_replicas = max(self.min_replicas, int(cfg.max_replicas))
        self._hot_streak = 0           # consecutive overloaded polls
        self._idle_since: Optional[float] = None
        self._last_event_ts: Optional[float] = None

    # ------------------------------------------------------------- triggers

    def _overloaded(self, obs: Observation) -> bool:
        if obs.pressured > 0:
            return True
        capacity = max(obs.live, 1)
        return obs.queue_depth > self.cfg.up_queue_per_replica * capacity

    def _idle(self, obs: Observation) -> bool:
        return obs.queue_depth == 0 and obs.active_lanes == 0

    # -------------------------------------------------------------- verdict

    def observe(self, obs: Observation,
                now: Optional[float] = None) -> Optional[str]:
        """Feed one poll's gauges; returns ``"up"``/``"down"``/``None``.
        The caller performs the mechanism and the policy's cooldown
        starts at the verdict — a failed spawn still debounces (the
        condition that caused it is still being answered)."""
        if now is None:
            now = time.monotonic()
        if obs.warming > 0:
            # warming capacity is an answer in flight: no verdict either
            # direction until it lands (false-flap guard, pinned test)
            self._hot_streak = 0
            self._idle_since = None
            return None
        overloaded = self._overloaded(obs)
        self._hot_streak = self._hot_streak + 1 if overloaded else 0
        if self._idle(obs):
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None
        if self._last_event_ts is not None \
                and (now - self._last_event_ts) < self.cfg.cooldown_s:
            return None
        if overloaded and self._hot_streak >= max(1, self.cfg.up_after) \
                and obs.live + obs.warming < self.max_replicas:
            self._last_event_ts = now
            self._hot_streak = 0
            return SCALE_UP
        if self._idle_since is not None \
                and (now - self._idle_since) >= self.cfg.down_idle_s \
                and obs.live - obs.draining > self.min_replicas:
            self._last_event_ts = now
            self._idle_since = None
            return SCALE_DOWN
        return None

    def describe(self, obs: Observation) -> str:
        """The reason string a verdict records (scale-event ledger)."""
        if obs.pressured > 0:
            return f"deadline_pressure={obs.pressured}"
        if self._overloaded(obs):
            return (f"queue={obs.queue_depth}>"
                    f"{self.cfg.up_queue_per_replica}x{max(obs.live, 1)}")
        return f"idle_trough>={self.cfg.down_idle_s}s"


__all__ = ["AUTOSCALER_RANK", "SCALE_UP", "SCALE_DOWN", "Observation",
           "ScaleEvent", "AutoscalePolicy"]
