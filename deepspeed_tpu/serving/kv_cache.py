"""Paged KV cache: a fixed pool of blocks + per-sequence block tables.

The dense serving layout (``models/generation.init_cache``) preallocates
``[L, B, nh, max_len, hd]`` per batch — every sequence pays for its WORST
CASE length, and a finished sequence's slack is unreclaimable until the
whole batch drains. For a long-lived serving loop that is the capacity
bottleneck, not FLOPs. This module replaces it with the vLLM-style paged
layout:

* one preallocated device pool ``[L, nh, num_blocks * block_size, hd]``
  (per k and v) shared by every in-flight sequence;
* a host-side :class:`BlockPool` allocator handing out fixed-size blocks
  with REFERENCE COUNTS — ``fork`` shares blocks between sequences
  (prefix-cache reuse for common system prompts) and a block returns to
  the free list when its last holder releases it;
* a :class:`PrefixCache` mapping token-prefix hashes to full-block runs
  of previously prefilled prompts, so a new request sharing a prompt
  prefix skips recomputing (and re-storing) those blocks entirely.

Copy-on-write discipline: blocks are shared at FULL-BLOCK granularity
only (a forked prefix always ends on a block boundary), and a sequence
only ever writes K/V at logical positions >= its fork point — which land
in its own private blocks. Shared blocks are therefore read-only by
construction; no device-side copy is ever needed, and the refcount is
the entire consistency protocol.

Physical block 0 is the NULL block: never allocated, the write target of
padded/inactive lanes in the fixed-shape decode step, and never read
(every read is masked by the per-sequence context length).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..testing import chaos

#: physical block 0 — the write sink for padded lanes, never allocated
NULL_BLOCK = 0


class BlockPoolExhausted(RuntimeError):
    """Allocation would exceed the pool — the scheduler's signal to keep
    the request QUEUED (admission control), never a crash."""


def init_pool(cfg, num_blocks: int, block_size: int,
              dtype=None) -> Dict[str, jnp.ndarray]:
    """Device-side paged pool: k/v ``[L, nh, num_blocks*block_size, hd]``.

    Flat slot layout (slot = block * block_size + offset) so the decode
    step's K/V write is ONE scatter over the slot axis; the paged-attention
    kernel views the same buffer as ``[L, nh, num_blocks, block_size, hd]``
    (a free reshape) to DMA whole blocks through the block table.

    ``dtype=jnp.int8`` (round 12): the quantized pool tier — k/v store
    int8 with a per-(layer, head, slot) f32 scale (symmetric over the
    head dim, ``quant_format.kv_quantize`` — the single-sourced format),
    halving pool HBM vs bf16. The paged forward quantizes on write;
    reads dequantize IN-kernel (round 17): the Pallas paged-attention
    kernel takes the int8 blocks plus scales and dequantizes per block
    in VMEM, so int8 is what crosses HBM (no pool-slice f32 copy)."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, cfg.num_heads, num_blocks * block_size,
             cfg.head_dim)
    if dtype == jnp.int8:
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
                "v_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32)}
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


class BlockPool:
    """Host-side block allocator with refcounts (see module docstring).

    ``num_blocks`` COUNTS the reserved null block: a pool of N blocks has
    N - 1 allocatable.

    Thread-safe (round 12): disaggregated serving shares ONE pool between
    prefill-role and decode-role replicas on different threads, so
    alloc/fork/release are atomic under an internal lock. ``free_count``
    probes stay optimistic — a racing allocation after a passing probe
    surfaces as :class:`BlockPoolExhausted`, which every admission path
    already treats as keep-queued."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("pool needs >= 2 blocks (block 0 is the null "
                             "block)")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free: List[int] = list(range(num_blocks - 1, NULL_BLOCK, -1))
        self._refs: Dict[int, int] = {}
        self._mu = threading.Lock()

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        return -(-max(int(n_tokens), 0) // self.block_size)

    def alloc(self, n: int) -> List[int]:
        """``n`` fresh private blocks (refcount 1). Raises
        :class:`BlockPoolExhausted` when the pool can't cover them — and
        the ``serve.oom`` failpoint can force that path (chaos tests pin
        queued-not-crashed)."""
        chaos.failpoint("serve.oom")
        with self._mu:
            if n > len(self._free):
                raise BlockPoolExhausted(
                    f"need {n} blocks, {len(self._free)} free "
                    f"(pool {self.num_blocks - 1} x {self.block_size} "
                    "tokens)")
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._refs[b] = 1
            return out

    def fork(self, blocks: Sequence[int]) -> List[int]:
        """Share ``blocks`` with another holder: +1 refcount each. The
        caller must treat them as READ-ONLY (full-block prefix sharing
        guarantees it never writes below its fork point)."""
        with self._mu:
            for b in blocks:
                if b == NULL_BLOCK or b not in self._refs:
                    raise ValueError(f"fork of unallocated block {b}")
            for b in blocks:
                self._refs[b] += 1
            return list(blocks)

    def release(self, blocks: Sequence[int]) -> None:
        """Drop one reference per block; a block returns to the free list
        when its last holder releases it."""
        with self._mu:
            for b in blocks:
                refs = self._refs.get(b)
                if refs is None:
                    raise ValueError(f"release of unallocated block {b}")
                if refs > 1:
                    self._refs[b] = refs - 1
                else:
                    del self._refs[b]
                    self._free.append(b)

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)


def _chain_keys(tokens: Sequence[int], block_size: int,
                max_blocks: int) -> List[str]:
    """Per-block-boundary prefix digests, computed INCREMENTALLY: key k
    hashes tokens[:k*block_size] by extending one running sha1, so the
    whole ladder costs O(len(tokens)) — not O(len^2 / block_size) as
    hashing each prefix from scratch would (admission is a hot path and
    prompts reach tens of thousands of tokens)."""
    keys: List[str] = []
    h = hashlib.sha1()
    for k in range(max_blocks):
        for t in tokens[k * block_size:(k + 1) * block_size]:
            h.update(int(t).to_bytes(4, "little", signed=True))
        keys.append(h.hexdigest())
    return keys


class PrefixCache:
    """Token-prefix hash -> full-block run of an already-prefilled prompt.

    Entries hold their own refcount on the blocks (via ``pool.fork``), so
    a cached prefix survives the request that created it; eviction (LRU,
    on allocation pressure) releases those references. Hash collisions
    are guarded by comparing the stored token prefix on match; entries of
    one insert share a single tokens tuple (no per-entry prefix copies)."""

    def __init__(self, pool: BlockPool):
        self.pool = pool
        # key -> (tokens ref, n_blocks, blocks, last_used)
        self._entries: Dict[str, Tuple[Tuple[int, ...], int, List[int],
                                       int]] = {}
        self._clock = 0
        # round 12: multiple prefill-role replicas share one cache —
        # match/insert/evict are atomic (RLock: clear() calls evict())
        self._mu = threading.RLock()

    def __len__(self) -> int:
        return len(self._entries)

    def _lookup(self, tokens: Sequence[int]
                ) -> Tuple[int, Optional[str], List[int]]:
        """(n_cached_tokens, entry key, blocks) of the longest cached
        full-block prefix — NO fork, no LRU touch."""
        bs = self.pool.block_size
        max_blocks = (len(tokens) - 1) // bs
        if max_blocks <= 0 or not self._entries:
            return 0, None, []
        keys = _chain_keys(tokens, bs, max_blocks)
        for k in range(max_blocks, 0, -1):
            ent = self._entries.get(keys[k - 1])
            if ent is None:
                continue
            etoks, ek, blocks, _ = ent
            if ek != k or tuple(etoks[:k * bs]) != \
                    tuple(int(t) for t in tokens[:k * bs]):
                continue                       # hash collision — skip
            return k * bs, keys[k - 1], blocks
        return 0, None, []

    def peek(self, tokens: Sequence[int]) -> Tuple[int, Optional[str]]:
        """Admission-budget probe: (n_cached_tokens, entry key) WITHOUT
        taking a reference — the scheduler uses it to net the hit out of
        the block budget and to protect the entry from its own
        make-room eviction."""
        with self._mu:
            n, key, _ = self._lookup(tokens)
            return n, key

    def match(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached FULL-BLOCK prefix of ``tokens``, capped at
        ``len(tokens) - 1`` so a fully-cached prompt still leaves >= 1
        token to prefill (the last prompt token's logits seed sampling).
        Returns ``(n_cached_tokens, forked_blocks)`` — the blocks already
        carry the caller's refcount."""
        with self._mu:
            n, key, blocks = self._lookup(tokens)
            if key is None:
                return 0, []
            self._clock += 1
            ent = self._entries[key]
            self._entries[key] = (ent[0], ent[1], ent[2], self._clock)
            return n, self.pool.fork(blocks)

    def insert(self, tokens: Sequence[int], blocks: Sequence[int]) -> None:
        """Register every full-block prefix of a prefilled prompt. The
        cache forks (refcounts) the blocks it retains; duplicate keys are
        refreshed, not re-forked."""
        bs = self.pool.block_size
        nfull = len(tokens) // bs
        if nfull <= 0:
            return
        shared = tuple(int(t) for t in tokens[:nfull * bs])
        keys = _chain_keys(shared, bs, nfull)
        with self._mu:
            for k in range(1, nfull + 1):
                key = keys[k - 1]
                self._clock += 1
                ent = self._entries.get(key)
                if ent is not None and ent[1] == k \
                        and ent[0][:k * bs] == shared[:k * bs]:
                    self._entries[key] = (ent[0], ent[1], ent[2],
                                          self._clock)
                    continue
                held = self.pool.fork(list(blocks[:k]))
                self._entries[key] = (shared, k, held, self._clock)

    def evict(self, need_blocks: int,
              protect: Optional[str] = None) -> int:
        """Release least-recently-used entries until ``need_blocks`` are
        free in the pool (or nothing evictable remains). Returns entries
        evicted. ``protect`` exempts one entry key — the prefix the
        admission candidate itself is about to reuse must not be the
        victim of its own make-room pass. Releasing an entry only frees
        blocks no live request still holds — refcounts make eviction
        safe mid-flight."""
        evicted = 0
        with self._mu:
            while self.pool.free_count < need_blocks:
                victims = [k for k in self._entries if k != protect]
                if not victims:
                    break
                key = min(victims, key=lambda k: self._entries[k][3])
                _, _, blocks, _ = self._entries.pop(key)
                self.pool.release(blocks)
                evicted += 1
        return evicted

    def clear(self) -> None:
        self.evict(self.pool.num_blocks)


class SharedPagedState:
    """The paged-KV state a disaggregated prefill/decode pair SHARES
    (round 12, serving/disagg.py): one device pool dict, one refcounted
    :class:`BlockPool`, one :class:`PrefixCache` — so a prefill role can
    hand finished blocks to a decode role by transferring block IDs, with
    zero device-side copies (the handoff moves logical ownership, never
    bytes).

    ``device_lock`` serializes the roles' jitted calls: both programs
    DONATE the pool buffers (the in-place-update discipline of
    serving/engine.py), so exactly one program may hold the live buffer
    at a time — each call takes the pools, runs, and writes the returned
    pools back under the lock. A single-threaded engine pays one
    uncontended acquire per step."""

    def __init__(self, cfg, serving, dtype=None):
        self.pool = BlockPool(serving.pool_blocks, serving.block_size)
        self.pools: Dict[str, Any] = init_pool(
            cfg, serving.pool_blocks, serving.block_size, dtype=dtype)
        self.prefix_cache = (PrefixCache(self.pool)
                             if serving.prefix_cache else None)
        self.device_lock = threading.Lock()

    def run(self, fn, params, *args):
        """Execute ``fn(params, pools, *args) -> (out, new_pools)`` with
        the live pool buffers, serialized against the other role."""
        with self.device_lock:
            # the lock MUST span fn: it donates self.pools, and the other
            # role dispatching against donated-invalidated buffers is the
            # exact aliasing bug this class exists to prevent
            out, self.pools = fn(params, self.pools, *args)  # graftlint: disable=TPU017
            return out
