"""Paged transformer forward: generation-path numerics over a block pool.

One pure function, :func:`paged_forward`, serves BOTH serving regimes:

* **prefill** — B=1, T = padded prompt(-suffix) length: writes the
  prompt's K/V into the sequence's pool blocks and returns logits for
  every query position (the host samples at the last REAL position);
* **decode** — B = max_batch (the padded active set), T=1: one fresh
  token per lane, fixed shapes across admissions/evictions so the jit
  NEVER re-specializes (the serving loop compiles exactly one decode
  step — CUDA-graph discipline, enforced by tests).

It mirrors ``models/generation.forward_with_cache`` numerically (same
layer math, same f32 score path, same -1e30 masking), so a paged serve
is token-exact with sequential ``generate()`` calls under greedy
sampling. The differences are mechanical: K/V land in pool slots via one
scatter per layer instead of a dynamic-update-slice into a dense cache,
and attention reads ride ``ops.attention.paged_attention`` — the Pallas
block-table kernel on TPU decode, the exact jnp gather reference
elsewhere.

Inactive / padded lanes are harmless by construction: their block tables
are all-NULL, their writes land in the null block, and their outputs are
discarded by the host. No per-sample left-pad machinery is needed —
paged sequences are always exact-length.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generation import _dense, _kv_quantize, _layer_norm, _moe_mlp
from ..models.transformer import TransformerConfig
from ..ops.attention import paged_attention

PyTree = Any


def paged_forward(cfg: TransformerConfig,
                  params: PyTree,
                  input_ids: jnp.ndarray,
                  pools: Dict[str, jnp.ndarray],
                  block_tables: jnp.ndarray,
                  q_start: jnp.ndarray,
                  context_lens: jnp.ndarray,
                  block_size: int,
                  *,
                  interpret: bool = False
                  ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Run T tokens per lane at logical positions [q_start, q_start + T)
    against the paged pool. Returns (logits [B, T, V] f32, updated pools).

    input_ids: [B, T]. pools: {"k","v"} [L, nh, num_slots, hd]
    (``serving.kv_cache.init_pool`` layout; ``num_slots`` = pool blocks x
    ``block_size``). block_tables: [B, max_blocks_per_seq] i32 — logical
    block j of lane b is physical pool block ``block_tables[b, j]``.
    q_start: [B] i32 — first query's logical position (tokens already in
    the cache below it are attended: a prefix-cache hit prefills only the
    suffix). context_lens: [B] i32 — total valid tokens INCLUDING the
    real queries of this call; query positions >= context_lens are
    PADDING (their K/V writes route to the null block, their logits are
    garbage the host never reads). ``block_size`` is static — it shapes
    the compiled scatter/gather.

    Params must be the scan-layers layout (``ensure_scan_layout``).
    post-LN encoders don't decode; int8 weight-only params work unchanged
    (the dequant rides ``_kernel_of``).

    int8 KV pools (round 12, in-kernel since round 17): when ``pools``
    carries ``k_scale`` / ``v_scale`` (``init_pool(dtype=jnp.int8)``),
    K/V rows are QUANTIZED ON WRITE — symmetric int8 over the head dim
    with one f32 scale per (layer, head, slot), the single-sourced
    ``quant_format.kv_quantize`` format — and the int8 pool plus scales
    go STRAIGHT to attention: the Pallas decode kernel DMAs int8 blocks
    through the block table and dequantizes them in VMEM; the jnp
    reference dequantizes after its gather. Either way the dequant is
    O(attended blocks), not O(pool) — the round-12 full-pool-slice
    f32 read copy is gone (ROADMAP item-2 rung, this PR). Error per
    element is bounded by that row's absmax / 254; greedy decodes are
    token-for-token identical to the round-12 path (gather and dequant
    are elementwise, so they commute).

    int8 weights (round 17): ``kernel_qscale`` leaves (engine-packed
    under ``serving.weight_dtype: "int8"``) route every block matmul
    through ``ops.pallas.quant_matmul`` — blockwise dequant in-kernel,
    jnp per-block reference elsewhere.
    """
    if cfg.post_ln:
        raise NotImplementedError("post-LN encoders (BERT) do not serve")
    if "blocks" not in params:
        raise ValueError("paged_forward needs scan-layers params "
                         "(models.generation.ensure_scan_layout)")
    B, T = input_ids.shape
    nbk = block_tables.shape[1]
    bs = int(block_size)
    k_pool, v_pool = pools["k"], pools["v"]
    quant_kv = "k_scale" in pools
    if k_pool.dtype == jnp.int8 and not quant_kv:
        raise ValueError(
            "int8 KV pool without k_scale/v_scale leaves — build pools "
            "with serving.kv_cache.init_pool(dtype=jnp.int8)")
    num_slots = k_pool.shape[2]
    if num_slots % bs:
        raise ValueError(f"pool slots {num_slots} not divisible by "
                         f"block_size {bs}")
    nb_pool = num_slots // bs
    L = cfg.num_layers
    nh, hd = cfg.num_heads, cfg.head_dim
    kvh = cfg.kv_heads
    rms = cfg.norm == "rmsnorm"
    from ..models.transformer import _ACTIVATIONS, alibi_slopes, apply_rotary
    act = _ACTIVATIONS[cfg.activation]
    sm_scale = (cfg.attn_scale if cfg.attn_scale is not None
                else 1.0 / np.sqrt(hd))

    bt = jnp.asarray(block_tables, jnp.int32)
    q_start = jnp.asarray(q_start, jnp.int32).reshape(B)
    ctx = jnp.asarray(context_lens, jnp.int32).reshape(B)
    # interpret threads into the weight path too: blockwise-int8 kernels
    # (kernel_qscale) route through the Pallas quant matmul
    dense = partial(_dense, interpret=interpret)

    wte = params["wte"]["embedding"]
    x = wte.astype(cfg.dtype)[input_ids]
    if cfg.embed_scale is not None:
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)

    pos = q_start[:, None] + jnp.arange(T)[None, :]        # [B, T] logical
    if cfg.pos_embed == "learned":
        wpe = params["wpe"]["embedding"].astype(cfg.dtype)
        x = x + wpe[jnp.minimum(pos, wpe.shape[0] - 1)]
    if cfg.embed_ln:
        x = _layer_norm(x, params["ln_emb"], cfg.layer_norm_eps, rms)

    slopes = (jnp.asarray(alibi_slopes(nh), jnp.float32)
              if cfg.pos_embed == "alibi" else None)
    windows = (jnp.asarray(cfg.layer_windows, jnp.int32)
               if cfg.layer_windows is not None
               else jnp.zeros((cfg.num_layers,), jnp.int32))

    # write slots: logical position p of lane b lives in pool slot
    # bt[b, p // bs] * bs + p % bs; PADDED positions (>= ctx) route to the
    # null block so the fixed-shape step can't corrupt live state
    blk = jnp.clip(pos // bs, 0, nbk - 1)                  # [B, T]
    off = pos % bs
    phys = jnp.take_along_axis(bt, blk, axis=1)            # [B, T]
    valid = pos < ctx[:, None]
    slots = jnp.where(valid, phys * bs + off, off)         # null block else
    flat_slots = slots.reshape(B * T)

    def layer(carry, xs):
        x, kv = carry
        k_pool, v_pool = kv["k"], kv["v"]
        p, window, li = xs
        h = _layer_norm(x, p["ln1"], cfg.layer_norm_eps, rms)
        qkv = dense(h, p["attn_qkv"])
        q, k, v = jnp.split(qkv, [nh * hd, (nh + kvh) * hd], axis=-1)
        to_heads = lambda t, n: t.reshape(B, T, n, hd).transpose(0, 2, 1, 3)
        q, k, v = to_heads(q, nh), to_heads(k, kvh), to_heads(v, kvh)
        if cfg.qk_norm:
            q = _layer_norm(q, p["q_norm"], cfg.layer_norm_eps, rms=True)
            k = _layer_norm(k, p["k_norm"], cfg.layer_norm_eps, rms=True)
        if cfg.pos_embed == "rotary":
            # table covers the pool's per-sequence maximum (nbk * bs) —
            # plain-theta tables are length-independent, so this matches
            # generate()'s cache-capacity table exactly
            inv_freq = cfg.rope_inv_freq(nbk * bs)
            q = apply_rotary(q, pos, cfg.rotary_dim, cfg.rotary_interleaved,
                             cfg.rope_theta, inv_freq=inv_freq)
            k = apply_rotary(k, pos, cfg.rotary_dim, cfg.rotary_interleaved,
                             cfg.rope_theta, inv_freq=inv_freq)
        if kvh != nh:
            # GQA: repeat kv to full heads before the pool write (the pool
            # stays [*, nh, ...] so the paged kernel applies unchanged)
            k = jnp.repeat(k, nh // kvh, axis=1)
            v = jnp.repeat(v, nh // kvh, axis=1)
        # ONE scatter per layer: [B, nh, T, hd] -> [B*T, nh, hd] rows into
        # flat slots (padded lanes hit the null block)
        k_rows = k.transpose(0, 2, 1, 3).reshape(B * T, nh, hd)
        v_rows = v.transpose(0, 2, 1, 3).reshape(B * T, nh, hd)
        kv_new = dict(kv)
        if quant_kv:
            # quantize-on-write: THE dense path's per-channel format
            # (same helper — axis=-1 math is rank-agnostic over rows)
            (kq, ks), (vq, vs) = _kv_quantize(k_rows), _kv_quantize(v_rows)
            k_pool = k_pool.at[li, :, flat_slots].set(kq)
            v_pool = v_pool.at[li, :, flat_slots].set(vq)
            kv_new["k_scale"] = kv["k_scale"].at[li, :, flat_slots].set(ks)
            kv_new["v_scale"] = kv["v_scale"].at[li, :, flat_slots].set(vs)
        else:
            k_pool = k_pool.at[li, :, flat_slots].set(
                k_rows.astype(k_pool.dtype))
            v_pool = v_pool.at[li, :, flat_slots].set(
                v_rows.astype(v_pool.dtype))
        kv_new["k"], kv_new["v"] = k_pool, v_pool
        # attention through the block table (kernel on TPU decode, exact
        # jnp gather elsewhere); the int8 tier passes the pool AS int8
        # with its scales — dequant happens in-kernel / post-gather,
        # O(attended blocks), never a pool-slice copy
        kp5 = k_pool.reshape(L, nh, nb_pool, bs, hd)
        vp5 = v_pool.reshape(L, nh, nb_pool, bs, hd)
        scale_kw = (dict(k_scale=kv_new["k_scale"],
                         v_scale=kv_new["v_scale"]) if quant_kv else {})
        o = paged_attention(q, kp5, vp5, bt, ctx, sm_scale=sm_scale,
                            alibi_slopes=slopes,
                            softcap=cfg.attn_softcap, window=window,
                            layer_idx=li, q_start=q_start,
                            interpret=interpret, **scale_kw)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, nh * hd)
        attn_out = dense(o, p["attn_proj"])
        if cfg.post_block_norms:
            attn_out = _layer_norm(attn_out, p["post_attn_norm"],
                                   cfg.layer_norm_eps, rms)

        def mlp(hin):
            if cfg.moe_experts > 0:
                return _moe_mlp(cfg, p["moe"], hin)
            if cfg.gated_mlp:
                g = act(dense(hin, p["mlp_gate"]))
                return dense(g * dense(hin, p["mlp_fc"]), p["mlp_proj"])
            return dense(act(dense(hin, p["mlp_fc"])), p["mlp_proj"])

        if cfg.parallel_residual:
            m_in = (_layer_norm(x, p["ln2"], cfg.layer_norm_eps, rms)
                    if cfg.parallel_residual_dual_ln else h)
            x_out = x + attn_out + mlp(m_in)
        else:
            x_mid = x + attn_out
            h2 = _layer_norm(x_mid, p["ln2"], cfg.layer_norm_eps, rms)
            m = mlp(h2)
            if cfg.post_block_norms:
                m = _layer_norm(m, p["post_mlp_norm"],
                                cfg.layer_norm_eps, rms)
            x_out = x_mid + m
        return (x_out, kv_new), None

    xs = (params["blocks"], windows, jnp.arange(cfg.num_layers))
    (x, kv_out), _ = jax.lax.scan(layer, (x, dict(pools)), xs)
    x = _layer_norm(x, params["ln_f"], cfg.layer_norm_eps, rms)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bth,vh->btv", x, wte.astype(x.dtype))
    else:
        logits = dense(x, params["lm_head"])
    if cfg.final_logit_softcap:
        from ..ops.attention import apply_softcap
        logits = apply_softcap(logits, cfg.final_logit_softcap)
    return logits.astype(jnp.float32), kv_out
