"""ServingEngine — the continuous-batching serving loop.

Turns (cfg, params) into a long-lived server: requests are submitted from
any thread, admitted under the block-pool budget, prefilled into paged KV
blocks (reusing cached prefix blocks for shared system prompts), and
decoded in-flight — new prefills join as finishing sequences free their
blocks, with NO batch-drain barrier.

Fixed-shape discipline: the decode step is ONE jitted program over
``max_batch`` lanes and a ``[max_batch, max_blocks_per_seq]`` block
table. Admissions, evictions and completions only change the DATA in
those arrays, never their shapes, so the loop compiles exactly one
decode step for its whole lifetime (pinned by tests via
``_cache_size``); prefills compile once per block-rounded prompt-suffix
bucket. This is the role CUDA-graph capture plays in the reference's
``InferenceEngine`` — here XLA's compile cache IS the graph cache, and
the fixed shapes are what keep it hot.

Supervision: each loop iteration stamps a ``SERVE`` heartbeat phase
(runtime/heartbeat.py), so the PR-6 watchdog/health stack bounds a wedged
serving loop exactly the way it bounds a wedged train step —
``watchdog.serve_timeout`` in ds_config arms the rc-117 deadline.

Token-exactness: greedy serving output is token-exact with sequential
``models.generation.generate()`` calls (same layer math, same f32 score
path — see serving/model_runner.py), which the integration tests pin
across staggered arrivals and mixed lengths.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generation import ensure_scan_layout
from ..models.transformer import TransformerConfig
from ..runtime.heartbeat import PHASE_SERVE
from ..testing import chaos
from ..utils.logging import log_dist, logger
from .kv_cache import (NULL_BLOCK, BlockPoolExhausted, SharedPagedState)
from .model_runner import paged_forward
from .scheduler import (BATCH, FAILED, FINISHED, PREFILL, PRIORITY_TIERS,
                        QUEUED, RUNNING, STANDARD, TIER_RANK, TIMEOUT,
                        Request, Scheduler)

PyTree = Any

_KV_DTYPES = {"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
              "f32": jnp.float32, "float32": jnp.float32,
              "int8": jnp.int8, None: None}


def resolve_kv_dtype(serving):
    """``serving.kv_cache_dtype`` -> jnp dtype (None = model dtype);
    shared by engine construction and the disagg pair's shared-state
    builder so both roles resolve identically."""
    if serving.kv_cache_dtype not in _KV_DTYPES:
        raise ValueError(
            f"serving.kv_cache_dtype={serving.kv_cache_dtype!r} is not "
            f"supported; choose one of "
            f"{sorted(k for k in _KV_DTYPES if k)} or null for the "
            "model dtype")
    return _KV_DTYPES[serving.kv_cache_dtype]


def lane_topk_topp(logits: jnp.ndarray, top_k: jnp.ndarray,
                   top_p: jnp.ndarray) -> jnp.ndarray:
    """Vectorized PER-LANE top-k / top-p filter for the compiled decode
    step (round 12): ``logits`` [B, V] (already temperature-scaled),
    ``top_k`` [B] i32 (<= 0 = off), ``top_p`` [B] f32 (>= 1 = off).

    Exactly ``models.generation._sample``'s masking math per lane — kth
    value keeps ties (every logit >= the kth largest survives), then HF
    TopPLogitsWarper nucleus semantics on the top-k-masked logits
    (``apply_top_p``: positional in the sorted order, top token always
    survives) — so a one-lane filter + categorical at the same key is
    token-identical to one-shot ``generate()`` sampling (pinned by
    test).

    ONE ordering pass: both filters read the same descending argsort
    (top-k masking only demotes a suffix of the sorted view, so the
    nucleus pass reuses the order), and the result scatters back through
    it — no second argsort, no inverse argsort."""
    B, V = logits.shape
    order = jnp.argsort(-logits, axis=-1)                        # [B, V]
    sl = jnp.take_along_axis(logits, order, axis=-1)             # desc
    k = jnp.clip(top_k, 1, V)
    kth = jnp.take_along_axis(sl, (k - 1)[:, None], axis=-1)     # [B, 1]
    keep_k = (top_k[:, None] <= 0) | (sl >= kth)
    probs = jax.nn.softmax(jnp.where(keep_k, sl, -1e30), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = ((cum - probs) < top_p[:, None]) | (top_p[:, None] >= 1.0)
    final_sorted = jnp.where(keep_k & keep_p, sl, -1e30)
    return jnp.full_like(logits, -1e30).at[
        jnp.arange(B)[:, None], order].set(final_sorted)


@dataclass
class _Seq:
    """One active lane: a RUNNING request's device-side bookkeeping."""
    req: Request
    blocks: List[int]                  # every block this seq holds
    table: np.ndarray                  # [max_blocks_per_seq] i32 physical ids
    ctx: int                           # tokens whose K/V is in the pool
    last_tok: int                      # sampled, not yet written back


@dataclass
class _Prefilling:
    """A prompt mid-chunked-prefill (round 12): blocks are fully
    allocated (admission control is unchanged — lifetime budget up
    front), ``done`` tokens of K/V are in the pool, and each loop
    iteration advances at most ``serving.prefill_chunk_tokens`` more —
    decode steps run in between, so a long prompt never stalls running
    lanes for more than one chunk."""
    req: Request
    blocks: List[int]
    table: np.ndarray
    done: int                          # tokens already in the pool
    total: int                         # == len(req.prompt)


class ServingEngine:
    """Continuous-batching server over a paged KV cache (module docstring).

    ``serving``: a ``config.config.ServingConfig`` (or plain dict of its
    fields). ``interpret=True`` runs the Pallas paged kernel interpreted
    (CPU tests); on the CPU backend the jnp gather reference is used
    automatically.
    """

    #: heartbeat-gauge role tag; disagg subclasses override (visible in
    #: ``dstpu health`` as ``role=PREFILL`` / ``role=DECODE``)
    role: Optional[str] = None

    def __init__(self,
                 cfg: TransformerConfig,
                 params: PyTree,
                 serving=None,
                 heartbeat=None,
                 rng: Optional[jax.Array] = None,
                 interpret: bool = False,
                 shared: Optional[SharedPagedState] = None):
        from ..config.config import ServingConfig
        if serving is None:
            serving = ServingConfig()
        elif isinstance(serving, dict):
            serving = ServingConfig(**serving)
        self.scfg = serving
        self.cfg = cfg
        bs = int(serving.block_size)
        self.block_size = bs
        self.max_batch = int(serving.max_batch)
        self.max_model_len = min(int(serving.max_blocks_per_seq) * bs,
                                 cfg.max_seq_len)
        self.nbk = -(-self.max_model_len // bs)      # table width
        self.interpret = interpret
        if cfg.rope_scaling_type == "dynamic":
            # dynamic NTK derives its table from the cache capacity, which
            # differs between the pool (max_blocks_per_seq * block_size)
            # and a one-shot generate() cache — serving would silently
            # break the token-exactness contract; linear/llama3 scaling is
            # length-independent and serves fine
            raise NotImplementedError(
                "serving does not support rope_scaling_type='dynamic' "
                "(length-dependent table); use linear/llama3 scaling or "
                "one-shot generate()")
        self.params = ensure_scan_layout(params, cfg.num_layers)
        kv_dtype = resolve_kv_dtype(serving)
        # int8 pools decode through the Pallas kernel's in-kernel dequant
        # tier (round 17) — the round-12 construction guard is gone.
        if serving.weight_dtype is not None:
            if serving.weight_dtype != "int8":
                raise ValueError(
                    f"serving.weight_dtype {serving.weight_dtype!r}: only "
                    "'int8' (blockwise weight-only) or null")
            # pack ONCE at construction: dense kernels -> blockwise int8
            # + per-256-element f32 scales (quant_format's wire format);
            # the decode matmuls then ride ops/pallas/quant_matmul and
            # never materialize a full-precision weight copy
            from ..ops.pallas.quant_matmul import pack_decode_weights
            self.params = pack_decode_weights(self.params)
        # the paged-KV state: PRIVATE by default, SHARED when a
        # disaggregated pair (serving/disagg.py) passes one in — block
        # IDs then mean the same pool slots to both roles, which is what
        # makes the prefill->decode handoff zero-copy
        self._shared = shared if shared is not None else SharedPagedState(
            cfg, serving, dtype=kv_dtype)
        self.scheduler = Scheduler(self.pool, serving.max_queue,
                                   self.max_model_len, self.prefix_cache,
                                   aging_s=serving.fleet.priority_aging_s,
                                   batch_highwater=serving.fleet
                                   .batch_highwater)
        self._slots: List[Optional[_Seq]] = [None] * self.max_batch
        self._prefilling: Optional[_Prefilling] = None
        self._warming = False      # role warms: no prefix-cache inserts
        self._chunk = int(serving.prefill_chunk_tokens)
        self._use_filters = bool(serving.sampling_filters)
        self._rng = rng if rng is not None else jax.random.PRNGKey(
            serving.seed)
        self._heartbeat = heartbeat
        self._watchdog = None
        self._lock = threading.Lock()
        self.steps = 0                     # decode steps executed
        self.stats: Dict[str, int] = {
            "completed": 0, "failed": 0, "timeout": 0,
            "tokens_generated": 0, "prefill_tokens": 0,
            "prefix_hit_tokens": 0, "preempted": 0}

        # ---- compiled programs (fixed shapes; ONE decode specialization) ----
        use_filters = self._use_filters

        def _pick(logits, r, temps, tks, tps):
            """Per-lane sampling: greedy lanes take argmax, temperature
            lanes a categorical over logits / temp — one compiled program
            for any mix. With ``serving.sampling_filters`` (a
            construction-time constant: the program is still compiled
            once) the vectorized per-lane top-k/top-p filter runs on the
            scaled logits first."""
            greedy = jnp.argmax(logits, axis=-1)
            scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
            if use_filters:
                scaled = lane_topk_topp(scaled, tks, tps)
            sampled = jax.random.categorical(r, scaled, axis=-1)
            return jnp.where(temps <= 0.0, greedy, sampled)

        def _decode(params, pools, toks, bt, ctx, r, temps, tks, tps):
            # toks [B] sit at logical position ctx[b]; after the write the
            # valid length is ctx + 1
            logits, pools = paged_forward(
                cfg, params, toks[:, None], pools, bt, ctx, ctx + 1, bs,
                interpret=self.interpret)
            return _pick(logits[:, -1], r, temps, tks, tps), pools

        def _prefill(params, pools, ids, bt, q0, ctx, last_idx, r, temps,
                     tks, tps):
            logits, pools = paged_forward(
                cfg, params, ids, pools, bt, q0, ctx, bs,
                interpret=self.interpret)
            last = jax.lax.dynamic_index_in_dim(logits, last_idx, 1,
                                                keepdims=False)   # [1, V]
            return _pick(last, r, temps, tks, tps), pools

        # pools are donated: the loop's only live copy moves through the
        # step, so the update is in-place on TPU (no 2x pool HBM)
        self._decode_fn = jax.jit(_decode, donate_argnums=(1,))
        self._prefill_fn = jax.jit(_prefill, donate_argnums=(1,))
        log_dist(
            f"ServingEngine: pool={serving.pool_blocks}x{bs} tokens "
            f"(~{(serving.pool_blocks - 1) * bs} cacheable), "
            f"max_batch={self.max_batch}, max_model_len="
            f"{self.max_model_len}, prefix_cache={serving.prefix_cache}, "
            f"prefill_chunk={self._chunk or 'whole'}",
            ranks=[0])

    # -- the paged-KV state, possibly SHARED with a disagg partner role --

    @property
    def pool(self):
        return self._shared.pool

    @property
    def pools(self):
        return self._shared.pools

    @property
    def prefix_cache(self):
        return self._shared.prefix_cache

    def _run_device(self, fn, *args):
        """One jitted call over the live pool buffers (donation-safe
        under the shared state's device lock)."""
        return self._shared.run(fn, self.params, *args)

    # ------------------------------------------------------------- submission

    def submit(self, prompt: Sequence[int], max_new_tokens: int = 32,
               temperature: float = 0.0, eos_token_id: Optional[int] = None,
               on_finish=None, top_k=None, top_p=None,
               deadline_s: Optional[float] = None,
               priority: str = STANDARD) -> Request:
        """Enqueue a generation request (thread-safe); returns the live
        :class:`Request` whose ``output_tokens``/``state`` the caller (or
        ``on_finish``) observes. ``deadline_s`` is a queue-wait TTL: a
        request still QUEUED that long after arrival is shed with a
        TIMEOUT result instead of waiting behind a too-big head forever
        (admitted requests always run to completion). ``priority``
        (round 19) picks the latency/standard/batch tier — dispatch
        order and the overload ladder's shed order; see
        docs/SERVING.md §Priority.

        ``top_k``/``top_p`` (round 12) require
        ``serving.sampling_filters`` — the vectorized per-lane filter
        rides the compiled decode step (one program for any mix of
        filtered/greedy lanes); with the flag off they raise, as the
        filter would put a [B, V] sort in every decode step."""
        if (top_k is not None or top_p is not None) \
                and not self._use_filters:
            raise NotImplementedError(
                "per-lane top_k/top_p need serving.sampling_filters=true "
                "(the nucleus filter adds a [B, V] sort to the compiled "
                "decode step); without it use greedy/temperature or "
                "one-shot generate()")
        if priority not in TIER_RANK:
            raise ValueError(f"unknown priority tier {priority!r}; pick "
                             f"one of {PRIORITY_TIERS}")
        req = Request(prompt=[int(t) for t in prompt],
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature),
                      top_k=int(top_k) if top_k is not None else None,
                      top_p=float(top_p) if top_p is not None else None,
                      eos_token_id=eos_token_id, on_finish=on_finish,
                      priority=priority)
        if deadline_s is not None:
            req.deadline_ts = req.arrival_ts + float(deadline_s)
        return self.scheduler.submit(req)

    # -------------------------------------------------------------- the loop

    @property
    def active(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    @property
    def idle(self) -> bool:
        return (self.active == 0 and self.scheduler.pending == 0
                and self._prefilling is None)

    @property
    def has_work(self) -> bool:
        """Would a :meth:`step` make progress? (fleet worker pacing)."""
        return bool(self.active or self.scheduler.pending
                    or self._prefilling is not None)

    @property
    def wants_dispatch(self) -> bool:
        """Should the fleet hand this engine another request? Keeping the
        per-engine queue empty IS the load balancing."""
        return self.scheduler.pending == 0 and self.active < self.max_batch

    def held_state(self, timeout: float = 1.0):
        """Death-path collection (disagg fleet): atomically detach and
        return ``(block_lists, requests)`` for every sequence this engine
        holds — decode lanes and any in-flight prefill — so a dead
        replica's share of a SHARED pool can be released once its thread
        is provably gone (releasing earlier could race the abandoned
        worker's final in-flight step). Returns None if the engine lock
        cannot be taken within ``timeout`` (a wedge inside a step): the
        caller parks and retries."""
        if not self._lock.acquire(timeout=timeout):
            return None
        try:
            blocks: List[List[int]] = []
            reqs: List[Request] = []
            if self._prefilling is not None:
                blocks.append(self._prefilling.blocks)
                reqs.append(self._prefilling.req)
                self._prefilling = None
            for i, s in enumerate(self._slots):
                if s is not None:
                    blocks.append(s.blocks)
                    reqs.append(s.req)
                    self._slots[i] = None
            self._collect_held(blocks, reqs)
            return blocks, reqs
        finally:
            self._lock.release()

    def _collect_held(self, blocks, reqs) -> None:
        """Subclass hook: detach role-specific block holders (runs under
        the engine lock inside :meth:`held_state`)."""

    def preempt_request(self, req: Request, timeout: float = 1.0) -> bool:
        """Evict ONE running decode lane mid-generation (round 19 tier
        preemption): under the engine lock the lane's blocks return to
        the pool, the slot frees, and the request reverts to QUEUED with
        its emitted tokens intact — the fleet's exactly-once requeue
        path resumes it from prompt + emitted, exactly the death-path
        contract (tokens decoded but never synced are dropped and
        regenerated identically under greedy). Only a RUNNING lane is
        preemptible: an in-flight prefill is about to finish paying for
        its blocks and evicting it frees no lane. Returns False when the
        request holds no lane here or the lock cannot be taken within
        ``timeout`` (a step in flight — the caller retries next poll)."""
        if not self._lock.acquire(timeout=timeout):
            return False
        try:
            for i, s in enumerate(self._slots):
                if s is not None and s.req is req:
                    self._slots[i] = None
                    self.pool.release(s.blocks)
                    req.state = QUEUED
                    self.stats["preempted"] += 1
                    return True
            return False
        finally:
            self._lock.release()

    def cancel_request(self, req: Request, timeout: float = 1.0) -> bool:
        """Withdraw a request wholesale (the process fleet's ``cancel``
        command): drop it from the scheduler queue if still queued, else
        evict its running lane. Never concludes the request — the hub
        owns its ledger and requeues it elsewhere."""
        if self.scheduler.withdraw(req):
            return True
        return self.preempt_request(req, timeout=timeout)

    def step(self) -> int:
        """One loop iteration: admit (whole prefill, or START a chunked
        one), advance an in-flight chunked prefill by AT MOST one chunk,
        then one fixed-shape decode step over the active set — so with
        ``serving.prefill_chunk_tokens > 0`` running lanes emit a token
        every iteration even while a long prompt prefills (the fairness
        bound tests pin). Returns requests completed this iteration."""
        with self._lock:
            done = self._admit()
            done += self._advance_prefill()
            if self.active:
                done += self._decode_step()
            self.steps += 1
            self.stats["timeout"] = self.scheduler.timed_out
            self._stamp_heartbeat()
            return done

    def run_until_idle(self, max_steps: int = 100_000) -> None:
        """Drive the loop until queue and lanes drain (tests, batch use)."""
        for _ in range(max_steps):
            if self.idle:
                return
            self.step()
        raise RuntimeError(f"serving loop not idle after {max_steps} steps")

    def run_forever(self, stop=None, idle_wait: float = 0.01) -> None:
        """The long-lived server entry: iterate until ``stop`` (a
        ``threading.Event``) is set, idle-waiting (and still stamping the
        SERVE heartbeat) between requests. The loop's EXIT is always
        stamped as a terminal heartbeat via :meth:`close` — a finished
        serving loop must read as a conclusion, never as rc-117 silence
        (``dstpu health`` shows ``clean exit``, not ``SILENT``)."""
        stop = stop if stop is not None else threading.Event()
        try:
            while not stop.is_set():
                if self.idle:
                    with self._lock:
                        self._stamp_heartbeat()
                    stop.wait(idle_wait)
                    continue
                self.step()
        finally:
            self.close()

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        # context-manager exit IS the loop exit: stamp the EXIT terminal
        # heartbeat so a drained-and-abandoned server never reads silent
        self.close()

    def generate_batch(self, prompts: Sequence[Sequence[int]],
                       max_new_tokens: int = 32, temperature: float = 0.0,
                       eos_token_id=None) -> List[List[int]]:
        """Convenience: submit all, drain, return outputs in order."""
        reqs = [self.submit(p, max_new_tokens, temperature,
                            eos_token_id=eos_token_id) for p in prompts]
        self.run_until_idle()
        return [r.output_tokens for r in reqs]

    # ----------------------------------------------------------- supervision

    def arm_watchdog(self, serve_timeout: float, **kw):
        """PR-6 stack: a serving loop that stops iterating for
        ``serve_timeout`` seconds is a wedge — rc 117, stack dumps, the
        launcher tears the world down."""
        from ..runtime.watchdog import StallWatchdog
        self._watchdog = StallWatchdog(
            stall_timeout=0.0, phase_timeouts={PHASE_SERVE: serve_timeout},
            phase=PHASE_SERVE, heartbeat=self._heartbeat, **kw).start()
        return self._watchdog

    def close(self) -> None:
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        if self._heartbeat is not None:
            try:
                from ..runtime.heartbeat import PHASE_EXIT
                self._heartbeat.stamp_terminal(PHASE_EXIT)
            except Exception:
                pass

    def _stamp_heartbeat(self) -> None:
        if self._watchdog is not None:
            self._watchdog.beat(self.steps)
        if self._heartbeat is not None:
            try:
                # queue-depth / active-lane gauges ride the record so
                # `dstpu health` shows load, not just liveness; disagg
                # roles also stamp role=PREFILL/DECODE
                gauges = {"queue": self.scheduler.pending,
                          "active": self.active,
                          "lanes": self.max_batch}
                if self.role is not None:
                    gauges["role"] = self.role
                self._heartbeat.write(PHASE_SERVE, self.steps, extra=gauges)
            except Exception:
                pass                      # diagnostics must not kill serving

    # ------------------------------------------------------------- admission

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _admission_capacity(self) -> bool:
        """Can a new prefill begin? Base engine: a free decode lane (the
        finished prefill needs one); disagg roles override."""
        return self._free_slot() is not None

    def _admit(self) -> int:
        """Fill free lanes from the queue head; returns requests that
        FINISHED during admission (max_new_tokens == 1 one-shots).
        Expired queued requests are shed first, even with every lane
        busy — the deadline bounds queue wait precisely when nothing can
        be admitted. With chunked prefill armed, admission only STARTS a
        prefill (allocates the lifetime blocks); the chunks themselves
        run one per loop iteration in :meth:`_advance_prefill`, so at
        most ONE request is admitted per iteration and decode is never
        blocked behind a whole long prompt."""
        self.scheduler.shed_expired()
        done = 0
        if self._chunked_mode():
            if self._prefilling is None and self._admission_capacity():
                req = self.scheduler.next_admission()
                if req is not None:
                    try:
                        self._prefilling = self._start_prefill(req)
                    except (BlockPoolExhausted, chaos.ChaosError) as e:
                        logger.warning("serving: admission of request %d "
                                       "deferred (%s)", req.rid, e)
                        self.scheduler.requeue_front(req)
            return done
        while self._free_slot() is not None:
            req = self.scheduler.next_admission()
            if req is None:
                return done
            try:
                done += self._prefill_request(req)
            except (BlockPoolExhausted, chaos.ChaosError) as e:
                # transient (chaos 'serve.oom' or a racing allocation):
                # the request goes back to the HEAD — queued, not crashed
                logger.warning("serving: admission of request %d deferred "
                               "(%s)", req.rid, e)
                self.scheduler.requeue_front(req)
                return done
        return done

    def _chunked_mode(self) -> bool:
        return self._chunk > 0

    def _start_prefill(self, req: Request) -> _Prefilling:
        """Allocate a request's LIFETIME blocks (admission control is
        identical to whole prefill) and stage it for chunked prefill."""
        P = len(req.prompt)
        req.state = PREFILL
        n_pref, forked = (self.prefix_cache.match(req.prompt)
                          if self.prefix_cache is not None else (0, []))
        try:
            total_blocks = self.pool.blocks_for_tokens(
                P + max(req.max_new_tokens - 1, 0))
            priv = self.pool.alloc(total_blocks - len(forked))
        except BaseException:
            if forked:
                self.pool.release(forked)
            req.state = QUEUED
            raise
        blocks = list(forked) + priv
        table = np.full((self.nbk,), NULL_BLOCK, np.int32)
        table[:len(blocks)] = blocks
        req.prefix_hit_tokens = n_pref
        req.prefill_progress = n_pref
        self.stats["prefix_hit_tokens"] += n_pref
        return _Prefilling(req, blocks, table, done=n_pref, total=P)

    def _advance_prefill(self) -> int:
        """Run AT MOST one chunk of the in-flight chunked prefill (the
        ``serve.chunk`` failpoint fires per chunk). On the final chunk
        the next token is sampled from the last real position's logits
        and the sequence is installed — into a decode lane here, into
        the block handoff for a disagg prefill role."""
        pf = self._prefilling
        if pf is None:
            return 0
        req = pf.req
        n = (pf.total - pf.done if self._chunk <= 0
             else min(self._chunk, pf.total - pf.done))
        chunk_toks = req.prompt[pf.done:pf.done + n]
        Tb = -(-n // self.block_size) * self.block_size
        ids = np.zeros((1, Tb), np.int32)
        ids[0, :n] = chunk_toks
        self._rng, r = jax.random.split(self._rng)
        try:
            chaos.failpoint("serve.chunk")
            tok = self._run_device(
                self._prefill_fn, jnp.asarray(ids),
                jnp.asarray(pf.table[None]),
                jnp.asarray([pf.done], jnp.int32),
                jnp.asarray([pf.done + n], jnp.int32),
                jnp.asarray(n - 1, jnp.int32), r,
                jnp.asarray([req.temperature], jnp.float32),
                *self._filter_args(req))
        except BaseException as e:
            # a failed chunk must not leak the lifetime allocation —
            # release EVERYTHING (partial K/V is recomputed on retry; the
            # chunk progress survives on req.prefill_progress for the
            # fleet's death ledger). Chaos/interrupt-class escapes leave
            # the request QUEUED for a requeue path; a plain Exception is
            # a deterministic per-request failure
            self._prefilling = None
            self.pool.release(pf.blocks)
            if isinstance(e, Exception) \
                    and not isinstance(e, chaos.ChaosError):
                self.stats["failed"] += 1
                req._finish(FAILED, error=repr(e))
            else:
                req.state = QUEUED
            raise
        pf.done += n
        req.prefill_progress = pf.done
        self.stats["prefill_tokens"] += n
        if pf.done < pf.total:
            return 0                      # sampled token of a mid-chunk
            #                               call is discarded — only the
            #                               final chunk's is real
        self._prefilling = None
        first = int(np.asarray(tok)[0])
        req.first_token_ts = time.monotonic()
        req.output_tokens.append(first)
        self.stats["tokens_generated"] += 1
        if self.prefix_cache is not None and not self._warming:
            # a warm's dummy prompt must not fork blocks into the
            # (possibly SHARED) prefix cache on every launch/restart
            self.prefix_cache.insert(req.prompt,
                                     pf.blocks[:pf.total // self.block_size])
        seq = _Seq(req, pf.blocks, pf.table, pf.total, first)
        if req.max_new_tokens <= 1 or (req.eos_token_id is not None
                                       and first == req.eos_token_id):
            self._finish(seq)
            return 1
        self._install(seq)
        return 0

    def _install(self, seq: _Seq) -> None:
        """Place a fully-prefilled sequence where decode will find it —
        a free lane here; the disagg prefill role hands it off instead."""
        seq.req.state = RUNNING
        self._slots[self._free_slot()] = seq

    def _filter_args(self, *reqs):
        """(top_k [n] i32, top_p [n] f32) device args for the compiled
        sampler (0 / 1.0 = off; always passed so the program shape never
        depends on the traffic)."""
        tks = np.asarray([r.top_k or 0 for r in reqs], np.int32)
        tps = np.asarray([r.top_p if r.top_p is not None else 1.0
                          for r in reqs], np.float32)
        return jnp.asarray(tks), jnp.asarray(tps)

    def _prefill_request(self, req: Request) -> int:
        P = len(req.prompt)
        req.state = PREFILL
        n_pref, forked = (self.prefix_cache.match(req.prompt)
                          if self.prefix_cache is not None else (0, []))
        try:
            total_blocks = self.pool.blocks_for_tokens(
                P + max(req.max_new_tokens - 1, 0))
            priv = self.pool.alloc(total_blocks - len(forked))
        except BaseException:
            if forked:
                self.pool.release(forked)
            req.state = QUEUED
            raise
        blocks = list(forked) + priv
        table = np.full((self.nbk,), NULL_BLOCK, np.int32)
        table[:len(blocks)] = blocks
        req.prefix_hit_tokens = n_pref
        req.prefill_progress = n_pref
        self.stats["prefix_hit_tokens"] += n_pref

        # prefill the suffix, bucket-padded to a block multiple so the
        # compile count is bounded by max_blocks_per_seq
        suffix = req.prompt[n_pref:]
        Tb = -(-len(suffix) // self.block_size) * self.block_size
        ids = np.zeros((1, Tb), np.int32)
        ids[0, :len(suffix)] = suffix
        self._rng, r = jax.random.split(self._rng)
        try:
            tok = self._run_device(
                self._prefill_fn, jnp.asarray(ids),
                jnp.asarray(table[None]), jnp.asarray([n_pref], jnp.int32),
                jnp.asarray([P], jnp.int32),
                jnp.asarray(len(suffix) - 1, jnp.int32), r,
                jnp.asarray([req.temperature], jnp.float32),
                *self._filter_args(req))
        except BaseException as e:
            # a failed forward (device OOM, interrupt) must not leak the
            # refcounted blocks — capacity survives the exception. A
            # plain Exception is a deterministic per-request failure:
            # mark it FAILED (its owner/callback unblocks, stats record
            # it) before propagating; KeyboardInterrupt-class exits leave
            # it QUEUED for a resumed loop
            self.pool.release(blocks)
            if isinstance(e, Exception):
                self.stats["failed"] += 1
                req._finish(FAILED, error=repr(e))
            else:
                req.state = QUEUED
            raise
        first = int(np.asarray(tok)[0])
        req.first_token_ts = time.monotonic()
        req.output_tokens.append(first)
        req.prefill_progress = P
        self.stats["tokens_generated"] += 1
        self.stats["prefill_tokens"] += len(suffix)
        if self.prefix_cache is not None:
            self.prefix_cache.insert(req.prompt, blocks[:P // self.block_size])
        if req.max_new_tokens <= 1 or (req.eos_token_id is not None
                                       and first == req.eos_token_id):
            self._finish(_Seq(req, blocks, table, P, first))
            return 1
        req.state = RUNNING
        self._slots[self._free_slot()] = _Seq(req, blocks, table, P, first)
        return 0

    # ---------------------------------------------------------------- decode

    def _decode_step(self) -> int:
        B = self.max_batch
        toks = np.zeros((B,), np.int32)
        ctx = np.zeros((B,), np.int32)
        temps = np.zeros((B,), np.float32)
        tks = np.zeros((B,), np.int32)
        tps = np.ones((B,), np.float32)
        tables = np.full((B, self.nbk), NULL_BLOCK, np.int32)
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            toks[i] = s.last_tok
            ctx[i] = s.ctx
            temps[i] = s.req.temperature
            tks[i] = s.req.top_k or 0
            tps[i] = s.req.top_p if s.req.top_p is not None else 1.0
            tables[i] = s.table
        self._rng, r = jax.random.split(self._rng)
        nxt = self._run_device(
            self._decode_fn, jnp.asarray(toks), jnp.asarray(tables),
            jnp.asarray(ctx), r, jnp.asarray(temps), jnp.asarray(tks),
            jnp.asarray(tps))
        nxt = np.asarray(nxt)
        done = 0
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            s.ctx += 1
            tok = int(nxt[i])
            s.req.output_tokens.append(tok)
            s.last_tok = tok
            self.stats["tokens_generated"] += 1
            eos = (s.req.eos_token_id is not None
                   and tok == s.req.eos_token_id)
            if eos or len(s.req.output_tokens) >= s.req.max_new_tokens:
                self._slots[i] = None
                self._finish(s)
                done += 1
        return done

    def _finish(self, seq: _Seq) -> None:
        self.pool.release(seq.blocks)
        self.stats["completed"] += 1
        seq.req._finish(FINISHED)
