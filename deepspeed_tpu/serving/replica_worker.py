"""One fleet replica as a supervised OS PROCESS (round 18).

The round-11 fleet runs replica engines as threads sharing the hub
process; ``serving.fleet.placement: "process"`` moves each replica into
its own process — the fleet-across-a-pod shape, where a replica death is
a PROCESS death and the blast radius is one OS process, not one thread's
good behaviour. This module is the worker: the process-per-replica twin
of ``runtime/pipe/mpmd/stage_worker.py``.

Contract (the hub side lives in serving/procfleet.py):

* weights arrive via CHECKPOINT LOAD (``--params`` npz written by the
  hub with runtime/checkpointing.save_tree; the worker rebuilds the
  template with ``model.init`` and fills it with ``load_tree``) — no
  pickled live arrays cross the process boundary;
* the request/token streams ride the transfer fabric
  (``runtime/fabric``): one :class:`SocketEndpoint` dialing the hub's
  star, hello ``{"ident": "replica-N"}``, generation-fenced frames,
  bounded redial on mid-stream loss — a link partition is NOT worker
  death, the worker redials into a fresh hub generation and keeps
  serving;
* token emission is CUMULATIVE: every ``prog``/``done`` frame carries
  ALL tokens this leg generated plus the ``base`` (emitted-prefix
  length) from the dispatch, so duplicated or replayed frames are
  idempotent at the hub — the exactly-once ledger is hub-side
  arithmetic, not wire discipline;
* liveness rides the PR-6 heartbeat channel: SERVE records with
  queue/active/pool_used/pid gauges every loop iteration (``dstpu
  health`` shows per-process replica rows); silence or process exit is
  the ONLY death verdict the hub accepts;
* SIGTERM stamps PREEMPTED and exits rc 114 (the preemption contract);
  a ``stop`` command exits rc 0.

Chaos: the worker traverses the same gates the thread fleet does
(``serve.replica_kill`` / ``serve.replica_hang`` / ``serve.replica_slow``
keyed by replica index, where ``kill`` mode — os._exit(13) — finally
means what it says) plus the fabric's ``net.*`` failpoints on every
frame. Specs ride DSTPU_CHAOS in the env, armed by the hub for the
FIRST spawn only (StageWorkerSpec.env_first semantics: a one-shot crash
spec must not re-arm in the restarted process).
"""
# graftlint: disable-file=TPU013 (a replica worker is a SINGLE-process
# jax world by construction — the per-process guard does not apply)

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import sys
import time
from typing import Any, Dict, Optional


# --------------------------------------------------------------- wire helpers
# (shared with the hub: procfleet.py imports cfg_to_dict — the worker
# owns the config wire format because it is the one that must rebuild)

def cfg_to_dict(cfg) -> Dict[str, Any]:
    """TransformerConfig -> JSON-safe dict (dtype by numpy name; tuples
    serialize as lists and are restored by :func:`cfg_from_dict`)."""
    import numpy as np
    d = dataclasses.asdict(cfg)
    d["dtype"] = np.dtype(cfg.dtype).name
    return d


def cfg_from_dict(d: Dict[str, Any]):
    import numpy as np
    from ..models.transformer import TransformerConfig

    def tup(v):
        return tuple(tup(x) for x in v) if isinstance(v, list) else v

    d = {k: tup(v) for k, v in d.items()}
    d["dtype"] = np.dtype(d["dtype"])
    return TransformerConfig(**d)


# ------------------------------------------------------------------- the loop

def run_worker(args) -> int:
    from ..runtime.heartbeat import PHASE_EXIT, HeartbeatWriter

    idx = int(args.replica)
    hb = None
    if args.hb_dir:
        # refresh fast enough that a long compile never reads as silence
        # under the fleet's heartbeat_timeout (the writer's default 15s
        # refresher loses that race against a 10s timeout)
        hb = HeartbeatWriter(args.hb_dir, rank=idx,
                             min_interval=float(args.hb_interval),
                             refresh_interval=1.0)
    # everything past the writer's birth runs under its terminal-stamp
    # finally: a crash during model load / warmup must stamp EXIT, not
    # strand a stale INIT record the hub has to time out on
    try:
        return _run_worker_inner(args, idx, hb)
    finally:
        if hb is not None:
            hb.stamp_terminal(PHASE_EXIT, lock_timeout=2.0)


def _run_worker_inner(args, idx, hb) -> int:
    import jax
    import jax.numpy as jnp

    from ..exit_codes import PREEMPTION_EXIT_CODE
    from ..models.transformer import build_model
    from ..runtime.checkpointing import load_tree
    from ..runtime.fabric import (ChannelClosed, ChannelTimeout,
                                  RedialPolicy, SocketEndpoint)
    from ..runtime.heartbeat import PHASE_INIT, PHASE_PREEMPTED, PHASE_SERVE
    from ..testing import chaos
    from .engine import ServingEngine
    from .scheduler import FINISHED

    if hb is not None:
        hb.write(PHASE_INIT, 0, force=True, extra={"pid": os.getpid()})

    def on_sigterm(signum, frame):
        if hb is not None:
            hb.write(PHASE_PREEMPTED, 0, force=True, lock_timeout=2.0,
                     extra={"pid": os.getpid()})
        os._exit(PREEMPTION_EXIT_CODE)

    signal.signal(signal.SIGTERM, on_sigterm)

    with open(args.model_json) as f:
        cfg = cfg_from_dict(json.load(f))
    with open(args.serving_json) as f:
        scfg_d = json.load(f)
    model, cfg = build_model(cfg)
    # the template tree: load_tree restores BY STRUCTURE, so the worker
    # re-derives the exact init pytree the hub saved from
    like = model.init(jax.random.PRNGKey(0),
                      {"input_ids": jnp.zeros((1, 8), jnp.int32)})["params"]
    params = load_tree(args.params, like)
    eng = ServingEngine(cfg, params, serving=scfg_d)

    # warm OFF the serving path: compile prefill bucket + decode step
    # before saying "ready" — a restart that serves cold would eat the
    # compile on the first real request's latency
    warm = eng.submit([1, 2, 3], 2)
    while not warm.done:
        eng.step()

    ep = SocketEndpoint(
        (args.hub_host, int(args.hub_port)), f"replica-{idx}",
        hello={"replica": idx, "pid": os.getpid()},
        redial=RedialPolicy(attempts=int(args.redial_attempts),
                            base=0.05, dial_timeout=5.0),
        fence=True)
    try:
        return _serve_loop(args, idx, hb, ep, eng, chaos,
                           ChannelTimeout, ChannelClosed, FINISHED,
                           PHASE_SERVE)
    finally:
        # the ready-send and first stamp can raise too: the endpoint
        # closes on EVERY exit, not just the serve loop's
        try:
            ep.close()
        except OSError:
            pass


def _serve_loop(args, idx, hb, ep, eng, chaos, ChannelTimeout,
                ChannelClosed, FINISHED, PHASE_SERVE) -> int:
    ep.send({"cmd": "ready", "pid": os.getpid()}, key=str(idx))

    inflight: Dict[int, tuple] = {}    # rid -> (engine req, base)
    reported: Dict[int, int] = {}      # rid -> tokens already framed
    pending_done: Dict[int, dict] = {}  # rid -> done frame, until acked
    last_resend = time.monotonic()

    def flush(final_only: bool = False) -> None:
        """Emit cumulative prog/done frames for every tracked request.
        Cumulative + base means a frame lost to a redial (or duplicated
        by one) costs nothing: the next frame carries the superset.
        Done frames are AT-LEAST-ONCE: re-sent until the hub acks (the
        hub's apply is idempotent), so a conclusion lost to a torn
        stream cannot strand its request RUNNING forever."""
        nonlocal last_resend
        for rid in list(inflight):
            er, base = inflight[rid]
            toks = [int(t) for t in er.output_tokens]
            if er.done:
                frame = {"cmd": "done", "rid": rid, "base": base,
                         "state": er.state,
                         "error": getattr(er, "error", None),
                         "toks": toks}
                pending_done[rid] = frame
                ep.send(frame, key=str(idx))
                del inflight[rid]
                reported.pop(rid, None)
            elif not final_only and len(toks) > reported.get(rid, 0):
                ep.send({"cmd": "prog", "rid": rid, "base": base,
                         "toks": toks}, key=str(idx))
                reported[rid] = len(toks)
        now = time.monotonic()
        if pending_done and now - last_resend > 0.25:
            last_resend = now
            for frame in list(pending_done.values()):
                ep.send(frame, key=str(idx))

    def stamp() -> None:
        if hb is not None:
            hb.write(PHASE_SERVE, eng.steps, extra={
                "queue": eng.scheduler.pending, "active": eng.active,
                "pool_used": eng.pool.used_count, "pid": os.getpid(),
                "replica": idx})

    stamp()
    rc = 0
    try:
        while True:
            chaos.failpoint("serve.replica_hang", key=str(idx))
            chaos.failpoint("serve.replica_kill", key=str(idx))
            chaos.failpoint("serve.replica_slow", key=str(idx))
            # drain every queued hub frame before stepping
            while True:
                try:
                    meta, _ = ep.recv(timeout=0.0, key=str(idx))
                except ChannelTimeout:
                    break
                cmd = meta.get("cmd")
                if cmd == "stop":
                    raise SystemExit(0)
                if cmd == "ack":
                    pending_done.pop(int(meta["rid"]), None)
                    continue
                if cmd == "cancel":
                    # hub-side preemption (round 19): withdraw the lane
                    # or queued entry and FORGET the rid — the hub
                    # already owns the emitted ledger and requeues the
                    # request elsewhere; any frame this leg still sends
                    # for the rid is dropped by the hub's replica guard
                    rid = int(meta["rid"])
                    pair = inflight.pop(rid, None)
                    reported.pop(rid, None)
                    pending_done.pop(rid, None)
                    if pair is not None and not pair[0].done:
                        eng.cancel_request(pair[0], timeout=5.0)
                    continue
                if cmd == "serve":
                    rid = int(meta["rid"])
                    if rid in inflight or rid in pending_done:
                        continue        # re-dispatch after a redial for
                        #                 work this leg already has/served
                    emitted = [int(t) for t in meta.get("emitted", [])]
                    budget = int(meta["max_new_tokens"]) - len(emitted)
                    if budget <= 0:
                        ep.send({"cmd": "done", "rid": meta["rid"],
                                 "base": len(emitted), "state": FINISHED,
                                 "error": None, "toks": []}, key=str(idx))
                        continue
                    er = eng.submit(
                        list(meta["prompt"]) + emitted, budget,
                        temperature=float(meta.get("temperature", 0.0)),
                        eos_token_id=meta.get("eos"),
                        deadline_s=meta.get("deadline_s"))
                    inflight[int(meta["rid"])] = (er, len(emitted))
            if eng.has_work:
                eng.step()
            else:
                time.sleep(0.005)
            flush()                     # progress frames + done re-sends
            stamp()
    except SystemExit as e:
        rc = int(e.code or 0)
        try:
            flush(final_only=True)      # concluded work outlives the stop
        except OSError:
            pass
    except ChannelClosed:
        # hub gone and the redial ladder exhausted: nothing to serve
        # into — exit clean; the hub (if any) holds the requeue ledger
        rc = 0
    return rc


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="dstpu fleet replica worker")
    p.add_argument("--replica", type=int, required=True)
    p.add_argument("--hub-host", default="127.0.0.1")
    p.add_argument("--hub-port", type=int, required=True)
    p.add_argument("--params", required=True, help="flat-npz weights")
    p.add_argument("--model-json", required=True)
    p.add_argument("--serving-json", required=True)
    p.add_argument("--hb-dir", default="")
    p.add_argument("--hb-interval", type=float, default=0.25)
    p.add_argument("--redial-attempts", type=int, default=4)
    return p.parse_args(argv)


def main(argv=None) -> int:
    return run_worker(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
