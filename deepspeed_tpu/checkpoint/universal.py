"""Universal checkpoint inspection.

Capability slot of the reference's ``deepspeed/checkpoint/
deepspeed_checkpoint.py:37`` (DeepSpeedCheckpoint: enumerate a checkpoint's
layer/param structure across tp/pp shards) and ``universal_checkpoint.py``
(reshape to a topology-free layout). Here checkpoints are ALREADY
topology-free — every parameter is stored whole under its pytree path — so
the class is pure introspection: names, shapes, dtypes, lazy tensor access.
Cross-topology loading is just `engine.load_checkpoint` under any mesh (see
tests/test_checkpointing.py cross-topology round-trip).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np

from ..runtime.checkpointing import get_latest_tag, read_flat_npz


def _npz_headers(path: str) -> Dict[str, tuple]:
    """{key: (shape, dtype_str)} read from the npy HEADERS only — no tensor
    data is materialized (a 6.7B checkpoint inspects in milliseconds)."""
    import zipfile

    from numpy.lib import format as npfmt
    out = {}
    dtype_map = {}
    with zipfile.ZipFile(path) as zf:
        for name in zf.namelist():
            if not name.endswith(".npy"):
                continue
            key = name[:-4]
            with zf.open(name) as f:
                version = npfmt.read_magic(f)
                shape, _, dtype = npfmt._read_array_header(f, version)
            out[key] = (shape, str(dtype))
    meta = out.pop("__dtypes__", None)
    if meta is not None:
        with zipfile.ZipFile(path) as zf:
            with zf.open("__dtypes__.npy") as f:
                raw = np.lib.format.read_array(f)
        dtype_map = json.loads(bytes(raw).decode())
    for key, logical in dtype_map.items():
        if key in out:
            out[key] = (out[key][0], logical)
    return out


class DeepSpeedCheckpoint:
    """Inspector over a saved checkpoint directory. Shapes/dtypes come from
    the npy headers; tensor data loads lazily per get_parameter call."""

    def __init__(self, ckpt_dir: str, tag: Optional[str] = None):
        if tag is None:
            tag = get_latest_tag(ckpt_dir)
            if tag is None:
                raise FileNotFoundError(
                    f"no 'latest' tag file in {ckpt_dir} — pass tag= "
                    "explicitly to inspect a specific checkpoint")
        self.dir = os.path.join(ckpt_dir, tag)
        if not os.path.isdir(self.dir):
            raise FileNotFoundError(f"no checkpoint at {self.dir}")
        self.tag = tag
        with open(os.path.join(self.dir, "meta.json")) as f:
            self.meta = json.load(f)
        self._model_path = os.path.join(self.dir, "model_states.npz")
        self._model_hdrs = _npz_headers(self._model_path)
        optim_path = os.path.join(self.dir, "optim_states.npz")
        self._optim_hdrs = (_npz_headers(optim_path)
                            if os.path.exists(optim_path) else {})

    @property
    def global_step(self) -> int:
        return int(self.meta.get("step", 0))

    def parameter_names(self) -> List[str]:
        return sorted(self._model_hdrs)

    def optimizer_keys(self) -> List[str]:
        return sorted(self._optim_hdrs)

    def get_parameter(self, name: str) -> np.ndarray:
        return read_flat_npz(self._model_path)[name]

    def shapes(self) -> Dict[str, tuple]:
        return {k: shape for k, (shape, _) in self._model_hdrs.items()}

    def num_parameters(self) -> int:
        return int(sum(int(np.prod(shape)) if shape else 1
                       for shape, _ in self._model_hdrs.values()))

    def summary(self) -> Dict:
        return {"tag": self.tag, "step": self.global_step,
                "num_parameters": self.num_parameters(),
                "num_tensors": len(self._model_hdrs),
                "optimizer_tensors": len(self._optim_hdrs),
                "dtypes": sorted({dt for _, dt
                                  in self._model_hdrs.values()})}


def inspect_checkpoint(ckpt_dir: str, tag: Optional[str] = None) -> Dict:
    """One-call summary (the ds_report-style view of a checkpoint)."""
    return DeepSpeedCheckpoint(ckpt_dir, tag).summary()
