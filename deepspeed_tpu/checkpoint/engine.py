"""Pluggable checkpoint engines — sync, async (thread-offloaded) writers.

Capability parity with the reference's
``deepspeed/runtime/checkpoint_engine/checkpoint_engine.py`` (CheckpointEngine
ABC: create/save/load/commit) + the Nebula async engine (``nebula/``): the
engine abstraction lets save_checkpoint hand tensors to a writer that
persists them off the training thread; ``commit`` is the durability barrier.

The async engine gathers device arrays to host SYNCHRONOUSLY (cheap D2H,
and the training loop would otherwise race donated buffers) and performs
file IO on a worker thread — the part worth hiding, exactly what the
reference offloads to Nebula's service.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import logger


class CheckpointEngine:
    """reference: checkpoint_engine.py:19 — create/save/load/commit."""

    # sync engines may receive lazy (thunk-valued) flat dicts and stream
    # leaf-by-leaf; async engines need materialized arrays (the training
    # thread would otherwise race donated device buffers)
    wants_lazy = True

    def create(self, tag: str) -> None:
        """Start of a checkpoint under ``tag`` (logging/bookkeeping hook)."""

    def run(self, fn: Callable[[], Any]) -> None:
        """Execute ``fn`` with this engine's ordering guarantees (async:
        after all previously submitted saves)."""
        fn()

    def save(self, state_dict: Dict[str, Any], path: str) -> None:
        raise NotImplementedError

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        from ..runtime.checkpointing import read_flat_npz
        return read_flat_npz(path)

    def commit(self, tag: str) -> bool:
        """Durability barrier: returns when everything under ``tag`` is on
        disk (reference: engine.commit for Nebula's async persistence)."""
        return True


class NpzCheckpointEngine(CheckpointEngine):
    """Synchronous writer (the reference's TorchCheckpointEngine role)."""

    def save(self, state_dict: Dict[str, Any], path: str) -> None:
        from ..runtime.checkpointing import write_flat_npz
        write_flat_npz(state_dict, path)


class AsyncCheckpointEngine(CheckpointEngine):
    """File IO on a worker thread; commit() joins all pending writes.

    reference: nebula/ async persistence + checkpoint/constants tagging.
    """

    wants_lazy = False

    def __init__(self):
        # one worker => FIFO: anything run() after save() lands after it —
        # the `latest`-after-data guarantee depends on this, so the worker
        # count is not configurable
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="ckpt-writer")
        self._pending: List[Future] = []
        self._lock = threading.Lock()
        self._failed = False

    def save(self, state_dict: Dict[str, Any], path: str) -> None:
        from ..runtime.checkpointing import write_flat_npz

        def job():
            write_flat_npz(state_dict, path)
            return path

        self.run(job)

    def run(self, fn: Callable[[], Any]) -> None:
        # later jobs (e.g. the `latest` tag update) must not run after an
        # earlier write failed — `latest` would point at a corrupt checkpoint
        def guarded():
            if self._failed:
                raise RuntimeError(
                    "skipped: an earlier checkpoint write failed")
            try:
                return fn()
            except Exception:
                self._failed = True
                raise

        with self._lock:
            self._pending.append(self._pool.submit(guarded))

    def commit(self, tag: str) -> bool:
        with self._lock:
            pending, self._pending = self._pending, []
        ok = True
        for f in pending:
            try:
                f.result()
            except Exception as e:
                logger.error("async checkpoint write failed: %s", e)
                ok = False
        self._failed = False
        return ok

    def __del__(self):
        try:
            self._pool.shutdown(wait=False)
        except Exception:
            pass


def build_checkpoint_engine(config) -> CheckpointEngine:
    """Pick the writer from the ds_config (checkpoint.async_save, or the
    nebula section as its alias)."""
    async_save = bool(getattr(config.checkpoint, "async_save", False))
    if getattr(config, "nebula", None) is not None and config.nebula.enabled:
        async_save = True
    return AsyncCheckpointEngine() if async_save else NpzCheckpointEngine()
