"""Pluggable checkpoint engines — sync, async (thread-offloaded) writers.

Capability parity with the reference's
``deepspeed/runtime/checkpoint_engine/checkpoint_engine.py`` (CheckpointEngine
ABC: create/save/load/commit) + the Nebula async engine (``nebula/``): the
engine abstraction lets save_checkpoint hand tensors to a writer that
persists them off the training thread; ``commit`` is the durability barrier.

The async engine gathers device arrays to host SYNCHRONOUSLY (cheap D2H,
and the training loop would otherwise race donated buffers) and performs
file IO on a worker thread — the part worth hiding, exactly what the
reference offloads to Nebula's service.

Failure semantics (round-3: crash-safe checkpointing):

- transient IO errors (``OSError``) retry with bounded exponential backoff
  before counting as a failure;
- a failed write poisons only ITS OWN checkpoint generation (``create``
  starts a new one), so one bad tag never blocks subsequent saves;
- ``commit`` returns a :class:`CommitResult` naming exactly which
  paths/jobs failed (truthy on success — existing ``assert commit(...)``
  call sites keep working) and quarantines the failed tag's staging dir;
- ``close()`` is the explicit shutdown (``__del__`` remains a safety net).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.logging import logger


class CommitResult:
    """Outcome of a durability barrier. Truthy iff every write landed;
    ``failures`` lists (path-or-label, error) pairs so callers learn WHICH
    write failed, not just that one did."""

    __slots__ = ("failures",)

    def __init__(self, failures: Optional[List[Tuple[str, str]]] = None):
        self.failures: List[Tuple[str, str]] = list(failures or ())

    @property
    def ok(self) -> bool:
        return not self.failures

    def failed_paths(self) -> List[str]:
        return [path for path, _ in self.failures]

    def __bool__(self) -> bool:
        return self.ok

    def __repr__(self) -> str:
        if self.ok:
            return "CommitResult(ok)"
        return f"CommitResult(failures={self.failures!r})"


class CheckpointEngine:
    """reference: checkpoint_engine.py:19 — create/save/load/commit."""

    # sync engines may receive lazy (thunk-valued) flat dicts and stream
    # leaf-by-leaf; async engines need materialized arrays (the training
    # thread would otherwise race donated device buffers)
    wants_lazy = True

    def create(self, tag: str, stage_dir: Optional[str] = None) -> None:
        """Start of a checkpoint under ``tag``. ``stage_dir`` (when given)
        is the staging directory to quarantine if this tag's writes fail."""

    def run(self, fn: Callable[[], Any], label: Optional[str] = None) -> None:
        """Execute ``fn`` with this engine's ordering guarantees (async:
        after all previously submitted saves). ``label`` names the job in
        commit() failure reports."""
        fn()

    def save(self, state_dict: Dict[str, Any], path: str) -> None:
        raise NotImplementedError

    def load(self, path: str, map_location=None) -> Dict[str, Any]:
        from ..runtime.checkpointing import read_flat_npz
        return read_flat_npz(path)

    def commit(self, tag: str) -> CommitResult:
        """Durability barrier: returns when everything under ``tag`` is on
        disk (reference: engine.commit for Nebula's async persistence)."""
        return CommitResult()

    def close(self) -> CommitResult:
        """Release resources. Idempotent; engines with pending writes drain
        them first."""
        return CommitResult()


class NpzCheckpointEngine(CheckpointEngine):
    """Synchronous writer (the reference's TorchCheckpointEngine role)."""

    def save(self, state_dict: Dict[str, Any], path: str) -> None:
        from ..runtime.checkpointing import write_flat_npz
        write_flat_npz(state_dict, path)


class AsyncCheckpointEngine(CheckpointEngine):
    """File IO on a worker thread; commit() joins all pending writes.

    reference: nebula/ async persistence + checkpoint/constants tagging.
    """

    wants_lazy = False

    def __init__(self, max_retries: int = 3, retry_backoff: float = 0.05):
        # one worker => FIFO: anything run() after save() lands after it —
        # the `latest`-after-data guarantee depends on this, so the worker
        # count is not configurable
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="ckpt-writer")
        # (future, label, generation) — generation keys failure isolation
        # AND which staging dir to quarantine (commit may drain several
        # tags at once; quarantining "the current" stage dir would hit the
        # wrong tag's)
        self._pending: List[Tuple[Future, str, int]] = []
        self._lock = threading.Lock()
        self.max_retries = max_retries
        self.retry_backoff = retry_backoff
        self._gen = 0               # checkpoint generation (bumped by create)
        self._failed_gen = -1       # newest generation with a failed write
        self._gen_stage: Dict[int, Optional[str]] = {}
        self._closed = False

    def create(self, tag: str, stage_dir: Optional[str] = None) -> None:
        # a failed PREVIOUS tag must not poison this one: jobs carry their
        # generation, and the skip guard only fires within a generation
        with self._lock:
            self._gen += 1
            self._gen_stage[self._gen] = stage_dir

    def save(self, state_dict: Dict[str, Any], path: str) -> None:
        from ..runtime.checkpointing import write_flat_npz

        def job():
            write_flat_npz(state_dict, path)
            return path

        self.run(job, label=path)

    def run(self, fn: Callable[[], Any], label: Optional[str] = None) -> None:
        with self._lock:
            if self._closed:
                raise RuntimeError("AsyncCheckpointEngine is closed")
            gen = self._gen
        name = label or getattr(fn, "__name__", "<job>")

        def guarded():
            # later jobs (e.g. the finalize/`latest` step) must not run
            # after an earlier write OF THE SAME TAG failed — `latest`
            # would point at a corrupt checkpoint
            with self._lock:
                poisoned = self._failed_gen == gen
            if poisoned:
                raise RuntimeError(
                    f"skipped '{name}': an earlier write for this "
                    "checkpoint failed")
            attempt = 0
            while True:
                try:
                    return fn()
                except OSError as e:
                    # transient IO: bounded exponential backoff, full
                    # rewrite per attempt (writers are idempotent)
                    attempt += 1
                    if attempt > self.max_retries:
                        with self._lock:
                            self._failed_gen = gen
                        raise
                    delay = self.retry_backoff * (2 ** (attempt - 1))
                    logger.warning(
                        "checkpoint write '%s' failed (%s); retry %d/%d "
                        "in %.2fs", name, e, attempt, self.max_retries,
                        delay)
                    time.sleep(delay)
                except Exception:
                    with self._lock:
                        self._failed_gen = gen
                    raise

        with self._lock:
            self._pending.append((self._pool.submit(guarded), name, gen))

    def commit(self, tag: str) -> CommitResult:
        with self._lock:
            pending, self._pending = self._pending, []
        failures: List[Tuple[str, str]] = []
        failed_gens = []
        for fut, name, gen in pending:
            try:
                fut.result()
            except Exception as e:
                logger.error("async checkpoint write failed: %s: %s",
                             name, e)
                failures.append((name, f"{e.__class__.__name__}: {e}"))
                if gen not in failed_gens:
                    failed_gens.append(gen)
        if failed_gens:
            from ..runtime.checkpointing import quarantine_staging
            for gen in failed_gens:
                with self._lock:
                    stage_dir = self._gen_stage.get(gen)
                if stage_dir is not None:
                    quarantine_staging(stage_dir, reason=failures[0][1])
        with self._lock:
            drained = {gen for _, _, gen in pending}
            for gen in drained:
                if gen != self._gen:        # current tag may still add jobs
                    self._gen_stage.pop(gen, None)
        return CommitResult(failures)

    def close(self, wait: bool = True) -> CommitResult:
        """Drain pending writes (``wait=True``) and shut the worker down.
        Idempotent; ``save``/``run`` after close raise."""
        with self._lock:
            if self._closed:
                return CommitResult()
            self._closed = True
        result = self.commit("close") if wait else CommitResult()
        self._pool.shutdown(wait=wait)
        return result

    def __del__(self):
        try:
            self.close(wait=False)
        except Exception:
            pass


def build_checkpoint_engine(config) -> CheckpointEngine:
    """Pick the writer from the ds_config (checkpoint.async_save, or the
    nebula section as its alias)."""
    ckpt = config.checkpoint
    async_save = bool(getattr(ckpt, "async_save", False))
    if getattr(config, "nebula", None) is not None and config.nebula.enabled:
        async_save = True
    if async_save:
        return AsyncCheckpointEngine(
            max_retries=int(getattr(ckpt, "write_retries", 3)),
            retry_backoff=float(getattr(ckpt, "write_retry_backoff", 0.05)))
    return NpzCheckpointEngine()
