"""deepspeed_tpu.checkpoint — engines + universal-checkpoint utilities.

reference: deepspeed/runtime/checkpoint_engine/ (pluggable writers) and
deepspeed/checkpoint/ (DeepSpeedCheckpoint inspector + universal/reshape
machinery — largely mooted here because checkpoints are name-keyed whole
tensors, topology-free by construction).
"""

from .engine import (AsyncCheckpointEngine, CheckpointEngine, CommitResult,
                     NpzCheckpointEngine, build_checkpoint_engine)
from .universal import DeepSpeedCheckpoint, inspect_checkpoint

__all__ = ["CheckpointEngine", "NpzCheckpointEngine", "AsyncCheckpointEngine",
           "CommitResult", "build_checkpoint_engine", "DeepSpeedCheckpoint",
           "inspect_checkpoint"]
