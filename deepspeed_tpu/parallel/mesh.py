"""Device-mesh construction — the TPU-native replacement for process groups.

The reference maintains a registry of torch.distributed process groups
(``deepspeed/utils/groups.py``: data/model/expert(+data) groups). On TPU the
idiomatic equivalent is one ``jax.sharding.Mesh`` with named axes; every
"group" is an axis (or tuple of axes) of that mesh, and XLA emits collectives
over ICI/DCN from sharding annotations.

Axis layout (outer → inner, inner axes most ICI-local):

    pipe    pipeline-parallel stages          (reference: pipe axis, topology.py:243)
    data    pure data parallel / ZeRO shards  (reference: data axis + ZeRO partitions)
    expert  expert parallel, carved OUT OF data parallel exactly as the reference
            carves expert groups from DP ranks (utils/groups.py:109-262): non-expert
            params treat ("data","expert") jointly as the DP axis
    seq     sequence/context parallel (ring attention) — TPU-native addition
    model   tensor parallel (innermost: highest-traffic collectives ride ICI)
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ("pipe", "data", "expert", "seq", "model")

# Axes over which ZeRO shards non-expert params/grads/optimizer state. Expert
# params shard over ("data","seq") only (their "DP group" excludes the expert axis).
ZERO_AXES = ("data", "expert", "seq")
EXPERT_ZERO_AXES = ("data", "seq")
# Axes over which the global batch is split.
BATCH_AXES = ("data", "expert")


class MeshManager:
    """Builds and owns the session's device mesh; answers group-size queries.

    Capability parity with ``deepspeed/utils/groups.py`` accessors
    (_get_data_parallel_group/world_size etc.), rebuilt as mesh-axis queries.
    """

    def __init__(self,
                 devices: Optional[Sequence] = None,
                 pp_size: int = 1,
                 tp_size: int = 1,
                 sp_size: int = 1,
                 ep_size: int = 1,
                 dp_size: Optional[int] = None):
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        denom = pp_size * tp_size * sp_size * ep_size
        if n % denom != 0:
            raise ValueError(
                f"world size {n} not divisible by pipe({pp_size}) * model({tp_size}) "
                f"* seq({sp_size}) * expert({ep_size})")
        inferred_dp = n // denom
        if dp_size is not None and dp_size != inferred_dp:
            raise ValueError(f"dp_size={dp_size} inconsistent with world size {n}")
        self.shape = dict(zip(MESH_AXES, (pp_size, inferred_dp, ep_size, sp_size, tp_size)))
        dev_array = np.asarray(devices).reshape(*self.shape.values())
        self.mesh = Mesh(dev_array, MESH_AXES)

    # -- groups.py-compatible accessors --------------------------------------

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.shape.values())))

    def get_data_parallel_world_size(self) -> int:
        """DP degree as the reference defines it (includes ranks later carved for EP)."""
        return self.shape["data"] * self.shape["expert"] * self.shape["seq"]

    def get_model_parallel_world_size(self) -> int:
        return self.shape["model"]

    def get_pipe_parallel_world_size(self) -> int:
        return self.shape["pipe"]

    def get_expert_parallel_world_size(self) -> int:
        return self.shape["expert"]

    def get_sequence_parallel_world_size(self) -> int:
        return self.shape["seq"]

    def get_expert_data_parallel_world_size(self) -> int:
        return self.shape["data"] * self.shape["seq"]

    # -- sharding helpers -----------------------------------------------------

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, extra_batch_axes: Tuple[str, ...] = ()) -> NamedSharding:
        """Batch dim split over DP(+EP) axes; extra axes shard subsequent dims
        (e.g. ('seq',) shards dim 1 — the sequence dim — over the seq axis)."""
        return NamedSharding(self.mesh, P(BATCH_AXES, *extra_batch_axes))

    def local_batch_slice(self, global_batch: int) -> int:
        return global_batch // (self.shape["data"] * self.shape["expert"])

    def describe(self) -> str:
        return (f"Mesh(pipe={self.shape['pipe']}, data={self.shape['data']}, "
                f"expert={self.shape['expert']}, seq={self.shape['seq']}, "
                f"model={self.shape['model']})")


_GLOBAL_MESH: Optional[MeshManager] = None


def set_global_mesh(mm: MeshManager) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mm


def get_global_mesh() -> Optional[MeshManager]:
    return _GLOBAL_MESH


def build_mesh_from_config(config, devices: Optional[Sequence] = None) -> MeshManager:
    """Derive mesh axis sizes from a DeepSpeedConfig."""
    mm = MeshManager(
        devices=devices,
        pp_size=config.pipeline.stages,
        tp_size=config.tensor_parallel.tp_size,
        sp_size=config.sequence_parallel.sp_size,
        ep_size=config.moe.ep_size if config.moe.enabled else 1,
    )
    set_global_mesh(mm)
    return mm
