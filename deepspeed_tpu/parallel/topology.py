"""N-D cartesian process topology with named axes.

Capability parity with the reference's ``deepspeed/runtime/pipe/topology.py``
(ProcessTopology / PipeDataParallelTopology / PipeModelDataParallelTopology /
PipelineParallelGrid). Pure coordinate math — on TPU the actual communicator
objects dissolve into mesh axes (see parallel/mesh.py); this class remains the
single source of truth for rank <-> coordinate mapping, axis-local peer groups,
and the axis ordering used to build the ``jax.sharding.Mesh``.
"""

from __future__ import annotations

import itertools
from collections import namedtuple
from typing import Dict, List, Sequence


class ProcessTopology:
    """Maps global ranks onto an N-D grid of named axes.

    Axis order is outer-to-inner: the LAST axis varies fastest with rank
    (matching the reference's row-major layout, topology.py:9-230). On TPU,
    inner axes should be the high-bandwidth (ICI) ones.
    """

    def __init__(self, axes: Sequence[str], dims: Sequence[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        if len(set(axes)) != len(axes):
            raise ValueError(f"duplicate axis names in {axes}")
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self._coord_to_rank: Dict[tuple, int] = {}
        for rank, coord in enumerate(itertools.product(*[range(d) for d in self.dims])):
            self._coord_to_rank[coord] = rank
        self._rank_to_coord = {r: self.ProcessCoord(*c) for c, r in self._coord_to_rank.items()}

    def world_size(self) -> int:
        size = 1
        for d in self.dims:
            size *= d
        return size

    def get_rank(self, **coord_kwargs) -> int:
        if set(coord_kwargs.keys()) != set(self.axes):
            raise ValueError(f"expected axes {self.axes}, got {list(coord_kwargs)}")
        key = tuple(coord_kwargs[a] for a in self.axes)
        return self._coord_to_rank[key]

    def get_coord(self, rank: int):
        return self._rank_to_coord[rank]

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)] if axis in self.axes else 0

    def get_axis_names(self) -> List[str]:
        return list(self.axes)

    def get_rank_repr(self, rank: int, omit_axes=("data",), inner_sep="_", outer_sep="-") -> str:
        """String like 'pipe_00-model_00' used in checkpoint file names."""
        coord = self.get_coord(rank)
        parts = []
        for axis, idx in zip(self.axes, coord):
            if axis in omit_axes:
                continue
            parts.append(f"{axis}{inner_sep}{idx:02d}")
        return outer_sep.join(parts)

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """All peer groups along ``axis``: ranks that differ only in that coordinate."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for other_coord in itertools.product(*[range(self.get_dim(a)) for a in other_axes]):
            fixed = dict(zip(other_axes, other_coord))
            group = [self.get_rank(**{**fixed, axis: i}) for i in range(self.get_dim(axis))]
            lists.append(group)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        """Ranks whose coordinates match all given axis=value constraints."""
        out = []
        for rank in range(self.world_size()):
            coord = self.get_coord(rank)
            if all(getattr(coord, a) == v for a, v in filter_kwargs.items()):
                out.append(rank)
        return out

    def __str__(self):
        return f"ProcessTopology(axes={self.axes}, dims={self.dims})"


class PipeDataParallelTopology(ProcessTopology):
    """(pipe, data) grid; data innermost so DP peers are ICI-adjacent.

    reference: topology.py:232-241.
    """

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """(pipe, data, model) grid for 3D parallelism. reference: topology.py:243-248."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"], dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Per-rank view of a pipeline topology: stage ids, peer groups, tied-weight groups.

    Capability parity with reference topology.py:249-453, minus torch process-group
    construction (mesh axes subsume it).
    """

    def __init__(self, topology: ProcessTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self.world_size = topology.world_size()
        self.pipe_parallel_size = topology.get_dim("pipe") or 1
        self.data_parallel_size = topology.get_dim("data") or 1
        self.model_parallel_size = topology.get_dim("model") or 1
        coord = topology.get_coord(global_rank)
        self.stage_id = getattr(coord, "pipe", 0)
        self.data_parallel_id = getattr(coord, "data", 0)
        self.model_parallel_id = getattr(coord, "model", 0)

    def get_stage_id(self) -> int:
        return self.stage_id

    def get_data_parallel_id(self) -> int:
        return self.data_parallel_id

    def get_pipe_parallel_rank(self) -> int:
        return self.stage_id

    def get_data_parallel_rank(self) -> int:
        return self.data_parallel_id

    def get_model_parallel_rank(self) -> int:
        return self.model_parallel_id

    def get_pipe_parallel_world_size(self) -> int:
        return self.pipe_parallel_size

    def get_data_parallel_world_size(self) -> int:
        return self.data_parallel_size

    def get_model_parallel_world_size(self) -> int:
        return self.model_parallel_size

    def stage_to_global(self, stage_id: int) -> int:
        """Global rank of the same (data, model) coordinate at another pipeline stage."""
        kwargs = {"pipe": stage_id}
        if "data" in self._topo.axes:
            kwargs["data"] = self.data_parallel_id
        if "model" in self._topo.axes:
            kwargs["model"] = self.model_parallel_id
        return self._topo.get_rank(**kwargs)

    def pipe_group(self) -> List[int]:
        """All ranks in this rank's pipeline (same data/model coordinate)."""
        kwargs = {}
        if "data" in self._topo.axes:
            kwargs["data"] = self.data_parallel_id
        if "model" in self._topo.axes:
            kwargs["model"] = self.model_parallel_id
        return self._topo.filter_match(**kwargs)

    def dp_group(self) -> List[int]:
        kwargs = {"pipe": self.stage_id}
        if "model" in self._topo.axes:
            kwargs["model"] = self.model_parallel_id
        return self._topo.filter_match(**kwargs)

    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    def is_last_stage(self) -> bool:
        return self.stage_id == self.pipe_parallel_size - 1
