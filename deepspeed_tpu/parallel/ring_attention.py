"""Sequence/context parallelism: ring attention + Ulysses head-exchange.

The reference (v0.8.1) has NO sequence parallelism — its long-context story is
block-sparse attention kernels (ops/sparse_attention/, SURVEY §5). These two
schemes are the TPU-native long-context mechanisms that exceed that bar:

  ring_attention — K/V chunks rotate around the 'seq' ICI ring via ppermute
    while each rank holds its Q chunk; online-softmax accumulation merges
    per-chunk partial attention (same math as flash attention's k-loop, lifted
    to the mesh level). Peak memory per chip: O(S/sp), comm fully overlapped
    with the chunk matmuls by XLA's latency-hiding scheduler.

  ulysses_attention — all_to_all converts seq-sharding to head-sharding
    (each rank gets H/sp heads with the FULL sequence), runs dense/flash
    attention locally, and converts back (DeepSpeed-Ulysses layout, which
    landed in the reference line much later).

Both run inside partial-auto shard_map: manual over 'seq', everything else
(data/model/expert) stays with the auto partitioner. Accumulators and the
boundary crossing are f32 (see runtime/pipe/spmd.py for the XLA low-precision
collective bug); the rotating K/V stay in compute dtype on the wire.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _chunk_attn_update(q, k, v, m, l, acc, q_off, k_off, causal, sm_scale):
    """One online-softmax accumulation step against a K/V chunk.

    q [B,H,Sq,D]; k,v [B,H,Sk,D]; m,l [B,H,Sq,1] f32; acc [B,H,Sq,D] f32.
    q_off/k_off: absolute position offsets of the chunks (for causal mask).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(k_pos <= q_pos, s, NEG_INF)
    m_cur = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_cur)
    p = jnp.exp(s - m_cur)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return m_cur, l_new, acc_new


def ring_attention(q: jnp.ndarray,
                   k: jnp.ndarray,
                   v: jnp.ndarray,
                   *,
                   mesh=None,
                   causal: bool = True,
                   sm_scale: Optional[float] = None,
                   seq_axis: str = "seq") -> jnp.ndarray:
    """Ring attention over the seq mesh axis. q,k,v: [B, H, S, D], S sharded
    over seq_axis; returns [B, H, S, D] with the same layout."""
    if mesh is None:
        from .mesh import get_global_mesh
        mesh = get_global_mesh().mesh
    sp = mesh.shape[seq_axis]
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / float(np.sqrt(D))
    if sp == 1:
        from ..ops.attention import mha_reference
        return mha_reference(q, k, v, causal=causal, sm_scale=scale)

    compute_dtype = q.dtype

    def inner(q, k, v):
        q = q.astype(compute_dtype)
        k = k.astype(compute_dtype)
        v = v.astype(compute_dtype)
        r = jax.lax.axis_index(seq_axis)
        B, H, Sl, _ = q.shape
        q_off = r * Sl
        m = jnp.full((B, H, Sl, 1), NEG_INF, jnp.float32)
        l = jnp.zeros((B, H, Sl, 1), jnp.float32)
        acc = jnp.zeros((B, H, Sl, D), jnp.float32)
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        def step(carry, t):
            k_c, v_c, m, l, acc = carry
            src = (r - t) % sp                 # origin rank of current chunk
            m, l, acc = _chunk_attn_update(q, k_c, v_c, m, l, acc,
                                           q_off, src * Sl, causal, scale)
            k_c = jax.lax.ppermute(k_c, seq_axis, perm)
            v_c = jax.lax.ppermute(v_c, seq_axis, perm)
            return (k_c, v_c, m, l, acc), None

        (k, v, m, l, acc), _ = jax.lax.scan(
            step, (k, v, m, l, acc), jnp.arange(sp))
        l_safe = jnp.where(l == 0.0, 1.0, l)
        return (acc / l_safe).astype(jnp.float32)

    out = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(P(None, None, seq_axis), P(None, None, seq_axis),
                  P(None, None, seq_axis)),
        out_specs=P(None, None, seq_axis),
        axis_names={seq_axis},
        check_vma=False,
    )(q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
    return out.astype(compute_dtype)


def ulysses_attention(q: jnp.ndarray,
                      k: jnp.ndarray,
                      v: jnp.ndarray,
                      *,
                      mesh=None,
                      causal: bool = True,
                      sm_scale: Optional[float] = None,
                      seq_axis: str = "seq",
                      attn_impl: str = "reference") -> jnp.ndarray:
    """Ulysses-style: a2a seq-shard -> head-shard, full-seq attention, a2a back.

    Requires num_heads % sp == 0. q,k,v: [B, H, S, D], S sharded over seq_axis.
    """
    if mesh is None:
        from .mesh import get_global_mesh
        mesh = get_global_mesh().mesh
    sp = mesh.shape[seq_axis]
    D = q.shape[-1]
    H = q.shape[1]
    scale = sm_scale if sm_scale is not None else 1.0 / float(np.sqrt(D))
    from ..ops.attention import mha_reference
    if sp == 1:
        return mha_reference(q, k, v, causal=causal, sm_scale=scale)
    if H % sp != 0:
        raise ValueError(f"ulysses needs heads {H} divisible by sp {sp}")

    compute_dtype = q.dtype

    def inner(q, k, v):
        q = q.astype(compute_dtype)
        k = k.astype(compute_dtype)
        v = v.astype(compute_dtype)

        def to_heads(t):   # [B, H, Sl, D] -> [B, H/sp, S, D]
            return jax.lax.all_to_all(t, seq_axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def to_seq(t):     # [B, H/sp, S, D] -> [B, H, Sl, D]
            return jax.lax.all_to_all(t, seq_axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
        if attn_impl == "flash":
            from ..ops.pallas.flash_attention import flash_attention
            oh = flash_attention(qh, kh, vh, causal=causal, sm_scale=scale)
        else:
            oh = mha_reference(qh, kh, vh, causal=causal, sm_scale=scale)
        return to_seq(oh).astype(jnp.float32)

    out = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(P(None, None, seq_axis), P(None, None, seq_axis),
                  P(None, None, seq_axis)),
        out_specs=P(None, None, seq_axis),
        axis_names={seq_axis},
        check_vma=False,
    )(q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
    return out.astype(compute_dtype)
