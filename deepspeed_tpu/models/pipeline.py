"""Pipelined flagship transformer — the model-side of SPMD pipeline parallelism.

Parameter structure is IDENTICAL to the non-pipelined scan-layers Transformer
(models/transformer.py) — wte/wpe/blocks[L,...]/ln_f — so checkpoints move
freely between pp=1 and pp=N topologies (the reference needs an offline
3D-reshape tool for this, deepspeed/checkpoint/; here it is true by
construction). The apply path differs: blocks are reshaped [L,...] ->
[pp, L/pp, ...] and executed with runtime/pipe/spmd.pipeline_apply; embedding
and head run replicated on every pipe rank (redundant compute, zero
communication — tied-embedding gradients need no ReduceTiedGrads step, unlike
the reference's tied-weight allreduce, pipe/engine.py _exec_reduce_tied_grads).

Per-micro side inputs generalize both executors (round-3 Missing #3):
attention masks and dropout rng keys ride next to the activations; the rng
for a (micro, stage, layer) is fold_in(fold_in(fold_in(base, micro), stage),
layer) in BOTH the gpipe and 1F1B paths, so the two schedules produce
bit-identical dropout masks and their grads stay comparable.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime.pipe.spmd import (pipeline_apply, stack_stage_params,
                                 unstack_stage_params)
from .transformer import Block, Transformer, TransformerConfig

PyTree = Any


def _pad_mask(attention_mask):
    """[B, S] padding mask -> [B, 1, 1, S] boolean attention mask (matches
    models/transformer.Transformer's mask construction)."""
    if attention_mask is None:
        return None
    return attention_mask.astype(jnp.bool_)[:, None, None, :]


class PipelinedTransformer:
    """Engine-compatible model object (init/apply) that pipelines its blocks.

    n_micro: microbatches fed through the pipeline per train step (the
    reference's gradient_accumulation_steps == pipeline micro_batches,
    engine.py:  micro_batches = gas).
    backward: '1F1B' backward mode — 'recompute' (default; stage body re-run
    from the saved input, nothing but boundaries stored) or 'store' (vjp
    residuals ride the rings; no recompute, more live memory).
    """

    def __init__(self, cfg: TransformerConfig, pp: int, n_micro: int,
                 mesh=None, backward: str = "recompute"):
        if cfg.num_layers % pp != 0:
            raise ValueError(f"num_layers {cfg.num_layers} not divisible by "
                             f"pp {pp}")
        if backward not in ("recompute", "store"):
            raise ValueError(f"backward must be recompute|store, "
                             f"got {backward!r}")
        if cfg.layer_windows is not None:
            # the stage body calls blocks without the per-layer window arg;
            # silently running a Mistral-class model with GLOBAL attention
            # would be a wrong answer, not a degraded one
            raise NotImplementedError(
                "pipelined model does not thread per-layer sliding "
                "windows (layer_windows); run windowed models on the "
                "non-pipelined engine")
        for knob in ("embed_ln", "token_type_vocab", "mlm_head",
                     "no_lm_head"):
            # same fail-loud contract: the pipelined embed/head plumbing
            # implements none of these, and running without them (BLOOM's
            # ln_emb, BERT segments/MLM head) silently changes the math
            if getattr(cfg, knob):
                raise NotImplementedError(
                    f"pipelined model does not support {knob}; run this "
                    "architecture on the non-pipelined engine")
        self.cfg = cfg
        self.pp = pp
        self.n_micro = n_micro
        self.mesh = mesh
        self.backward = backward
        #: MPMD placement: per-(train, schedule, loss) pipeline objects —
        #: each holds its per-stage jit programs, so a training loop
        #: compiles each stage exactly once (runtime/pipe/mpmd/executor).
        self._mpmd_cache: Dict[Any, Any] = {}
        # reference model for param init: identical param structure
        self._ref = Transformer(
            cfg if cfg.scan_layers else
            TransformerConfig(**{**cfg.__dict__, "scan_layers": True}))
        self._block = Block(cfg)
        norm_cls = nn.RMSNorm if cfg.norm == "rmsnorm" else nn.LayerNorm
        self._ln_f = norm_cls(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                              param_dtype=jnp.float32, name="ln_f")

    # -- engine model contract -----------------------------------------------

    def init(self, rng, batch, **kwargs):
        return self._ref.init(rng, batch, **kwargs)

    def _parse_batch(self, batch):
        if isinstance(batch, dict):
            return (batch["input_ids"], batch.get("attention_mask"),
                    batch.get("labels"))
        return batch, None, None

    def _micro_extras(self, attention_mask, rng, train, B, S):
        """Per-micro side-input pytree for the executors: padding masks and
        per-micro dropout rng keys (folded further per stage and layer
        inside the stage body)."""
        cfg = self.cfg
        extras = {}
        if attention_mask is not None:
            extras["mask"] = attention_mask.reshape(
                self.n_micro, B // self.n_micro, S)
        if train and cfg.dropout > 0.0:
            if rng is None:
                raise ValueError("dropout>0 training needs an rng")
            extras["rng"] = jax.vmap(
                lambda i: jax.random.fold_in(rng, i))(
                    jnp.arange(self.n_micro))
        return extras

    def _embed_micros(self, embed_inputs, ids_micros, S):
        """[n_micro, mb, S] ids -> embedded activations. ``embed_inputs``
        holds the raw embedding tables ({"wte": [V,H]} plus "wpe" for
        learned positions) so the 1F1B path can jax.vjp through this
        directly. Rotary/ALiBi positions need nothing here — the blocks
        apply them internally from default arange positions."""
        cfg = self.cfg
        e = embed_inputs["wte"].astype(cfg.dtype)[ids_micros]
        if cfg.embed_scale is not None:
            e = e * jnp.asarray(cfg.embed_scale, cfg.dtype)
        if cfg.pos_embed == "learned":
            e = e + embed_inputs["wpe"].astype(cfg.dtype)[
                jnp.arange(S)][None, None]
        return e

    def _embed_inputs(self, params):
        out = {"wte": params["wte"]["embedding"]}
        if self.cfg.pos_embed == "learned":
            out["wpe"] = params["wpe"]["embedding"]
        return out

    def _head_logits(self, head_p, h):
        """Final-norm'd hidden states -> logits; tied einsum against wte or
        the untied (optionally biased) lm_head kernel. Applies the Gemma-2
        final-logit softcap (returns f32 then — every caller casts to f32
        anyway, and a bf16 round-trip of capped logits can flip near-tie
        argmaxes)."""
        if self.cfg.tie_embeddings:
            wte = head_p["wte"].astype(h.dtype)
            logits = jnp.einsum("...sh,vh->...sv", h, wte)
        else:
            k = head_p["lm_head"]["kernel"].astype(h.dtype)
            logits = jnp.einsum("...sh,hv->...sv", h, k)
            if "bias" in head_p["lm_head"]:
                logits = logits + head_p["lm_head"]["bias"].astype(h.dtype)
        if self.cfg.final_logit_softcap:
            from ..ops.attention import apply_softcap
            logits = apply_softcap(logits, self.cfg.final_logit_softcap)
        return logits

    def _head_params(self, params):
        head = {"ln_f": params["ln_f"]}
        if self.cfg.tie_embeddings:
            head["wte"] = params["wte"]["embedding"]
        else:
            head["lm_head"] = params["lm_head"]
        return head

    def _block_stage_fn(self, train):
        """stage_fn(block_stack, h, extra, stage) for both executors."""
        cfg = self.cfg
        moe = cfg.moe_experts > 0
        dropout = train and cfg.dropout > 0.0

        def stage_fn(block_stack, h, extra, stage):
            mask = _pad_mask(extra.get("mask")) \
                if isinstance(extra, dict) else None
            stage_rng = (jax.random.fold_in(extra["rng"], stage)
                         if dropout else None)
            n_layers = jax.tree.leaves(block_stack)[0].shape[0]

            def layer(carry, xs):
                h, li = carry
                p = xs
                rngs = {}
                if dropout:
                    rngs["dropout"] = jax.random.fold_in(stage_rng, li)
                if moe and stage_rng is not None:
                    # top-2 gating's Gumbel second pick; noise-free gating
                    # without a per-micro rng (the pre-round-4 behavior)
                    rngs["gating"] = jax.random.fold_in(stage_rng, 1000 + li)
                out, aux = self._block.apply(
                    {"params": p}, h, mask, train,
                    rngs=rngs or None)
                return (out, li + 1), aux

            (h, _), auxes = jax.lax.scan(
                layer, (h, jnp.zeros((), jnp.int32)), block_stack)
            if moe:
                return h, jnp.sum(auxes)
            return h

        return stage_fn

    def apply(self, variables, batch, train: bool = False, rngs=None,
              mesh=None):
        params = variables["params"]
        cfg = self.cfg
        mesh = mesh or self.mesh
        if mesh is None:
            from ..parallel.mesh import get_global_mesh
            mesh = get_global_mesh().mesh
        input_ids, attention_mask, _ = self._parse_batch(batch)
        B, S = input_ids.shape
        if B % self.n_micro != 0:
            raise ValueError(f"batch {B} not divisible by n_micro {self.n_micro}")
        if isinstance(rngs, dict):
            base_rng = rngs.get("dropout")
            if base_rng is None:
                base_rng = rngs.get("params")
        else:
            base_rng = rngs

        # reshape the INTEGER ids to microbatches first: ids carry no
        # cotangent, so the data-axis reshard of the [B]->[n_micro, mb] split
        # never transposes into a low-precision collective (XLA SPMD miscompiles
        # bf16 resharding copies on some backends)
        ids_micros = input_ids.reshape(self.n_micro, B // self.n_micro, S)
        micros = self._embed_micros(self._embed_inputs(params), ids_micros, S)
        # pin the microbatched layout: micro dim replicated, the PER-MICRO
        # batch dim carries the (data, expert) sharding. Left to inference
        # the partitioner may split the micro dim instead (seen on the
        # pp x ep ladder mesh), and the head's reshape back to [B, S, V]
        # then pays involuntary replicate-and-reshard round trips.
        from .transformer import _spec_constraint
        mspec = P(None, ("data", "expert"), None, None)
        micros = _spec_constraint(micros, mspec)
        stage_params = stack_stage_params(params["blocks"], self.pp)

        moe = cfg.moe_experts > 0
        extras = self._micro_extras(attention_mask, base_rng, train, B, S)
        stage_fn = self._block_stage_fn(train)

        res = pipeline_apply(stage_fn, stage_params, micros, mesh=mesh,
                             pp=self.pp, remat=cfg.remat, with_aux=moe,
                             extras=extras)
        outs, aux_total = res if moe else (res, None)
        outs = _spec_constraint(outs, mspec)
        # head runs per-micro; only the fp32 logits are reshaped back to the
        # flat batch (fp32 resharding avoids the bf16 SPMD copy bug above)
        h = self._ln_f.apply({"params": params["ln_f"]}, outs)
        logits = self._head_logits(self._head_params(params),
                                   h).astype(jnp.float32)
        logits = logits.reshape((B, S, cfg.vocab_size))
        logits = _spec_constraint(logits, P(("data", "expert"), None, None))
        if moe:
            return logits, aux_total
        return logits

    __call__ = apply

    # -- 1F1B training path --------------------------------------------------

    def train_value_and_grad(self, params, batch, mesh=None, rng=None,
                             loss_scale=None, loss_fn=None, train=True,
                             aux_weight=None):
        """Loss + grads via the hand-scheduled 1F1B executor
        (runtime/pipe/one_f_one_b): activation memory ∝ pp (not n_micro) and
        the boundary stays bf16. Returns (loss, grads) with grads matching
        the params tree.

        Accepts everything the gpipe path does (round-3 Missing #3 closed):
        attention_mask batches, dropout (per-micro/stage/layer rng folding,
        bit-identical to gpipe's), MoE (the aux scalar flows through the
        manual backward via its constant cotangent), fp16 loss scaling
        (``loss_scale`` seeds the backward; grads come out scaled for the
        engine's standard unscale/overflow tail), and a custom last-stage
        ``loss_fn(logits, micro_batch)`` (per-micro losses averaged over
        micros — the reference's _aggregate_total_loss semantics).
        """
        cfg = self.cfg
        mesh = mesh or self.mesh
        if mesh is None:
            from ..parallel.mesh import get_global_mesh
            mesh = get_global_mesh().mesh
        from ..runtime.pipe.one_f_one_b import pipeline_1f1b_value_and_grad
        input_ids, attention_mask, labels = self._parse_batch(batch)
        if labels is None:
            labels = input_ids
        B, S = input_ids.shape
        mb = B // self.n_micro
        ids_micros = input_ids.reshape(self.n_micro, mb, S)
        lab_micros = labels.reshape(self.n_micro, mb, S)

        micros, embed_vjp = jax.vjp(
            lambda ep: self._embed_micros(ep, ids_micros, S),
            self._embed_inputs(params))
        stage_params = stack_stage_params(params["blocks"], self.pp)
        extras = self._micro_extras(attention_mask, rng, train, B, S)
        stage_fn = self._block_stage_fn(train)
        moe = cfg.moe_experts > 0

        head = self._head_params(params)

        if loss_fn is None:
            # default causal-LM objective with GLOBAL token mean: the
            # executor averages per-micro losses, so each micro contributes
            # its nll SUM scaled by n_micro/total_valid — with unevenly
            # -100-masked micros a per-micro mean would overweight sparse
            # ones vs the gpipe/causal_lm_loss objective
            total_valid = jnp.maximum(
                jnp.sum((lab_micros[:, :, 1:] != -100).astype(jnp.float32)),
                1.0)

            def head_loss(head_p, y, lab):
                h = self._ln_f.apply({"params": head_p["ln_f"]}, y)
                logits = self._head_logits(head_p, h)
                logits = logits[:, :-1].astype(jnp.float32)
                tgt = lab[:, 1:]
                valid = tgt != -100
                safe = jnp.where(valid, tgt, 0)
                logz = jax.nn.logsumexp(logits, axis=-1)
                gold = jnp.take_along_axis(logits, safe[..., None],
                                           axis=-1)[..., 0]
                nll_sum = jnp.sum((logz - gold) * valid)
                return nll_sum * (self.n_micro / total_valid)

            head_labels = lab_micros
        else:
            # custom objective: loss_fn(model_output, micro_batch) per
            # micro, averaged over micros. EVERY [B, ...] leaf of the batch
            # reshapes to [n_micro, mb, ...]; batch-independent leaves ride
            # replicated per micro — the user's loss sees the same fields
            # it would on the gpipe schedule.
            def to_micros(leaf):
                leaf = jnp.asarray(leaf)
                if leaf.ndim >= 1 and leaf.shape[0] == B:
                    return leaf.reshape((self.n_micro, mb) + leaf.shape[1:])
                return jnp.broadcast_to(leaf[None],
                                        (self.n_micro,) + leaf.shape)

            micro_batches = (jax.tree.map(to_micros, batch)
                             if isinstance(batch, dict)
                             else {"input_ids": ids_micros,
                                   "labels": lab_micros})

            def head_loss(head_p, y, lab):
                h = self._ln_f.apply({"params": head_p["ln_f"]}, y)
                out = self._head_logits(head_p, h).astype(jnp.float32)
                return loss_fn(out, lab).astype(jnp.float32)

            head_labels = micro_batches

        aux_w = (aux_weight if aux_weight is not None
                 else cfg.moe_aux_weight)
        loss, aux, gs, gh, dmicros = pipeline_1f1b_value_and_grad(
            stage_fn, head_loss, stage_params, head, micros,
            lab_micros if loss_fn is None else head_labels,
            mesh=mesh, pp=self.pp, extras=extras,
            with_aux=moe,
            aux_cotangent=(aux_w if moe else 0.0),
            loss_scale=loss_scale,
            store_outputs=(self.backward == "store"))
        (dembed,) = embed_vjp(dmicros)
        dwte = dembed["wte"]
        if cfg.tie_embeddings:
            dwte = dwte + gh["wte"]           # head grad rides the tie
        grads = {
            "wte": {"embedding": dwte},
            "blocks": unstack_stage_params(gs),
            "ln_f": gh["ln_f"],
        }
        if cfg.pos_embed == "learned":
            grads["wpe"] = {"embedding": dembed["wpe"]}
        if not cfg.tie_embeddings:
            grads["lm_head"] = gh["lm_head"]
        if moe:
            # reported loss matches make_moe_loss: task + aux_weight * aux
            loss = loss + aux_w * aux
        return loss, grads

    # -- MPMD training path --------------------------------------------------

    def mpmd_value_and_grad(self, params, batch, mesh=None, rng=None,
                            loss_scale=None, loss_fn=None, train=True,
                            aux_weight=None, schedule="1f1b", channel=None):
        """Loss + grads via the MPMD placement (runtime/pipe/mpmd): each
        stage is its own jit program on its own submesh of ``mesh``'s
        'pipe' axis, activations/cotangents ride the explicit transfer
        channel, and the SAME clock tables as the SPMD executors drive
        the ticks (``schedule`` = 'gpipe' | '1f1b').

        Accepts the 1F1B path's full generality (masks, dropout rng
        folding — bit-identical per (micro, stage, layer) — MoE aux via
        its constant cotangent, fp16 loss_scale seeding, custom per-micro
        last-stage loss). The per-stage pipelines are cached on the
        model, so a training loop compiles each stage exactly once.
        ``backward='store'`` is SPMD-only (residual rings are a
        stacked-scan construct) and is refused loudly.
        """
        cfg = self.cfg
        if self.backward == "store":
            raise ValueError(
                "backward='store' is an SPMD-executor mode (vjp residual "
                "rings inside the stacked scan); the MPMD placement's "
                "fused per-stage backward is the recompute regime — "
                "build the model with backward='recompute'")
        mesh = mesh or self.mesh
        if mesh is None:
            from ..parallel.mesh import get_global_mesh
            mesh = get_global_mesh().mesh
        from ..runtime.pipe.mpmd.executor import MPMDPipeline
        input_ids, attention_mask, labels = self._parse_batch(batch)
        if labels is None:
            labels = input_ids
        B, S = input_ids.shape
        mb = B // self.n_micro
        ids_micros = input_ids.reshape(self.n_micro, mb, S)
        lab_micros = labels.reshape(self.n_micro, mb, S)

        micros, embed_vjp = jax.vjp(
            lambda ep: self._embed_micros(ep, ids_micros, S),
            self._embed_inputs(params))
        stage_params = stack_stage_params(params["blocks"], self.pp)
        extras = self._micro_extras(attention_mask, rng, train, B, S)
        moe = cfg.moe_experts > 0
        head = self._head_params(params)

        if loss_fn is None:
            # same GLOBAL token-mean objective as the 1F1B path — the
            # batch-dependent valid count rides the per-call ``loss_ctx``
            # arg so it never bakes into the cached per-stage trace
            loss_ctx = jnp.maximum(
                jnp.sum((lab_micros[:, :, 1:] != -100).astype(jnp.float32)),
                1.0)
            head_labels = lab_micros
        else:
            def to_micros(leaf):
                leaf = jnp.asarray(leaf)
                if leaf.ndim >= 1 and leaf.shape[0] == B:
                    return leaf.reshape((self.n_micro, mb) + leaf.shape[1:])
                return jnp.broadcast_to(leaf[None],
                                        (self.n_micro,) + leaf.shape)

            head_labels = (jax.tree.map(to_micros, batch)
                           if isinstance(batch, dict)
                           else {"input_ids": ids_micros,
                                 "labels": lab_micros})
            loss_ctx = ()

        # keyed on mesh and channel too: a later call with a different
        # mesh must NOT reuse submesh programs built for the old device
        # layout, and a caller-supplied channel is honored per call.
        # (Callers passing a fresh lambda loss_fn per call defeat the
        # cache — per-stage re-jits every step; pass a stable function.)
        key = (bool(train), schedule, loss_fn, moe, mesh,
               None if channel is None else id(channel))
        pipe = self._mpmd_cache.get(key)
        if pipe is None:
            n_micro = self.n_micro

            if loss_fn is None:
                def head_loss(head_p, y, lab, ctx):
                    h = self._ln_f.apply({"params": head_p["ln_f"]}, y)
                    logits = self._head_logits(head_p, h)
                    logits = logits[:, :-1].astype(jnp.float32)
                    tgt = lab[:, 1:]
                    valid = tgt != -100
                    safe = jnp.where(valid, tgt, 0)
                    logz = jax.nn.logsumexp(logits, axis=-1)
                    gold = jnp.take_along_axis(logits, safe[..., None],
                                               axis=-1)[..., 0]
                    nll_sum = jnp.sum((logz - gold) * valid)
                    return nll_sum * (n_micro / ctx)
            else:
                def head_loss(head_p, y, lab, ctx):
                    h = self._ln_f.apply({"params": head_p["ln_f"]}, y)
                    out = self._head_logits(head_p, h).astype(jnp.float32)
                    return loss_fn(out, lab).astype(jnp.float32)

            pipe = MPMDPipeline(self._block_stage_fn(train), head_loss,
                                pp=self.pp, schedule=schedule, mesh=mesh,
                                with_aux=moe, channel=channel)
            self._mpmd_cache[key] = pipe

        aux_w = (aux_weight if aux_weight is not None
                 else cfg.moe_aux_weight)
        loss, aux, gs, gh, dmicros = pipe.value_and_grad(
            stage_params, head, micros,
            lab_micros if loss_fn is None else head_labels,
            extras=extras, loss_ctx=loss_ctx,
            aux_cotangent=(aux_w if moe else 0.0),
            loss_scale=loss_scale)
        (dembed,) = embed_vjp(dmicros)
        dwte = dembed["wte"]
        if cfg.tie_embeddings:
            dwte = dwte + gh["wte"]
        grads = {
            "wte": {"embedding": dwte},
            "blocks": unstack_stage_params(gs),
            "ln_f": gh["ln_f"],
        }
        if cfg.pos_embed == "learned":
            grads["wpe"] = {"embedding": dembed["wpe"]}
        if not cfg.tie_embeddings:
            grads["lm_head"] = gh["lm_head"]
        if moe:
            loss = loss + aux_w * aux
        return loss, grads

    # -- sharding rules ------------------------------------------------------

    def tp_rules(self) -> Dict[str, P]:
        """Blocks lead with the 'pipe' axis on the layer dim; embed/head as in
        the non-pipelined rules."""
        def block(*spec):
            return P(*(("pipe",) + spec))

        return {
            r"blocks/.*attn_qkv/kernel": block(None, "model"),
            r"blocks/.*attn_qkv/bias": block("model"),
            r"blocks/.*attn_proj/kernel": block("model", None),
            r"blocks/.*mlp_fc/kernel": block(None, "model"),
            r"blocks/.*mlp_fc/bias": block("model"),
            r"blocks/.*mlp_gate/kernel": block(None, "model"),
            r"blocks/.*mlp_gate/bias": block("model"),
            r"blocks/.*mlp_proj/kernel": block("model", None),
            # MoE expert stacks [L, E, in, out]: the layer dim carries the
            # pipe axis (as for every block param), expert axis on E,
            # row/col TP inside — the non-pipelined rules with the layer
            # lead swapped from None to 'pipe'
            r"blocks/.*experts/fc/kernel": block("expert", None, "model"),
            r"blocks/.*experts/fc/bias": block("expert", "model"),
            r"blocks/.*experts/gate/kernel": block("expert", None, "model"),
            r"blocks/.*experts/gate/bias": block("expert", "model"),
            r"blocks/.*experts/proj/kernel": block("expert", "model", None),
            r"blocks/.*experts/proj/bias": block("expert", None),
            r"blocks/.*moe/gate/kernel": block(),
            r"blocks/": P("pipe"),           # ln scales/biases: pipe only
            r"wte/embedding": P("model", None),
            r"lm_head/kernel": P(None, "model"),
        }


def build_pipelined_model(name_or_cfg, pp: int, n_micro: int, **overrides):
    from .transformer import get_config
    backward = overrides.pop("backward", "recompute")
    cfg = (name_or_cfg if isinstance(name_or_cfg, TransformerConfig)
           else get_config(name_or_cfg, **overrides))
    return (PipelinedTransformer(cfg, pp=pp, n_micro=n_micro,
                                 backward=backward), cfg)
