"""Pipelined flagship transformer — the model-side of SPMD pipeline parallelism.

Parameter structure is IDENTICAL to the non-pipelined scan-layers Transformer
(models/transformer.py) — wte/wpe/blocks[L,...]/ln_f — so checkpoints move
freely between pp=1 and pp=N topologies (the reference needs an offline
3D-reshape tool for this, deepspeed/checkpoint/; here it is true by
construction). The apply path differs: blocks are reshaped [L,...] ->
[pp, L/pp, ...] and executed with runtime/pipe/spmd.pipeline_apply; embedding
and head run replicated on every pipe rank (redundant compute, zero
communication — tied-embedding gradients need no ReduceTiedGrads step, unlike
the reference's tied-weight allreduce, pipe/engine.py _exec_reduce_tied_grads).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime.pipe.spmd import pipeline_apply, stack_stage_params
from .transformer import Block, Transformer, TransformerConfig

PyTree = Any


class PipelinedTransformer:
    """Engine-compatible model object (init/apply) that pipelines its blocks.

    n_micro: microbatches fed through the pipeline per train step (the
    reference's gradient_accumulation_steps == pipeline micro_batches,
    engine.py:  micro_batches = gas).
    """

    def __init__(self, cfg: TransformerConfig, pp: int, n_micro: int,
                 mesh=None):
        if cfg.num_layers % pp != 0:
            raise ValueError(f"num_layers {cfg.num_layers} not divisible by "
                             f"pp {pp}")
        if cfg.dropout != 0.0:
            raise NotImplementedError("pipelined path does not thread dropout "
                                      "rngs yet; set dropout=0")
        if cfg.moe_experts > 0:
            raise NotImplementedError("MoE + pipeline composition lands with "
                                      "aux-loss threading through the pipe "
                                      "loop; use pp=1 for MoE models")
        self.cfg = cfg
        self.pp = pp
        self.n_micro = n_micro
        self.mesh = mesh
        # reference model for param init: identical param structure
        self._ref = Transformer(
            cfg if cfg.scan_layers else
            TransformerConfig(**{**cfg.__dict__, "scan_layers": True}))
        self._block = Block(cfg)
        self._ln_f = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                  param_dtype=jnp.float32, name="ln_f")

    # -- engine model contract -----------------------------------------------

    def init(self, rng, batch, **kwargs):
        return self._ref.init(rng, batch, **kwargs)

    def apply(self, variables, batch, train: bool = False, rngs=None,
              mesh=None):
        params = variables["params"]
        cfg = self.cfg
        mesh = mesh or self.mesh
        if mesh is None:
            from ..parallel.mesh import get_global_mesh
            mesh = get_global_mesh().mesh
        if isinstance(batch, dict) and batch.get("attention_mask") is not None:
            raise NotImplementedError(
                "PipelinedTransformer does not thread attention_mask through "
                "the pipe loop yet; pad-free batches only (use pp=1 for "
                "masked batches)")
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        B, S = input_ids.shape
        if B % self.n_micro != 0:
            raise ValueError(f"batch {B} not divisible by n_micro {self.n_micro}")

        wte = params["wte"]["embedding"]            # [V, H] fp32
        wpe = params["wpe"]["embedding"]            # [T, H]
        # reshape the INTEGER ids to microbatches first: ids carry no
        # cotangent, so the data-axis reshard of the [B]->[n_micro, mb] split
        # never transposes into a low-precision collective (XLA SPMD miscompiles
        # bf16 resharding copies on some backends)
        ids_micros = input_ids.reshape(self.n_micro, B // self.n_micro, S)
        micros = (wte.astype(cfg.dtype)[ids_micros] +
                  wpe.astype(cfg.dtype)[jnp.arange(S)][None, None, :])
        stage_params = stack_stage_params(params["blocks"], self.pp)

        def stage_fn(block_stack, h):
            # scan this stage's L/pp blocks (same compiled body per layer)
            def layer(carry, p):
                out, _aux = self._block.apply({"params": p}, carry, None, train)
                return out, None
            h, _ = jax.lax.scan(layer, h, block_stack)
            return h

        outs = pipeline_apply(stage_fn, stage_params, micros, mesh=mesh,
                              pp=self.pp, remat=cfg.remat)
        # head runs per-micro; only the fp32 logits are reshaped back to the
        # flat batch (fp32 resharding avoids the bf16 SPMD copy bug above)
        h = self._ln_f.apply({"params": params["ln_f"]}, outs)
        logits = jnp.einsum("nbsh,vh->nbsv", h,
                            wte.astype(cfg.dtype)).astype(jnp.float32)
        return logits.reshape((B, S, cfg.vocab_size))

    __call__ = apply

    # -- sharding rules ------------------------------------------------------

    def tp_rules(self) -> Dict[str, P]:
        """Blocks lead with the 'pipe' axis on the layer dim; embed/head as in
        the non-pipelined rules."""
        def block(*spec):
            return P(*(("pipe",) + spec))

        return {
            r"blocks/.*attn_qkv/kernel": block(None, "model"),
            r"blocks/.*attn_qkv/bias": block("model"),
            r"blocks/.*attn_proj/kernel": block("model", None),
            r"blocks/.*mlp_fc/kernel": block(None, "model"),
            r"blocks/.*mlp_fc/bias": block("model"),
            r"blocks/.*mlp_proj/kernel": block("model", None),
            r"blocks/": P("pipe"),           # ln scales/biases: pipe only
            r"wte/embedding": P("model", None),
            r"lm_head/kernel": P(None, "model"),
        }


def build_pipelined_model(name_or_cfg, pp: int, n_micro: int, **overrides):
    from .transformer import get_config
    cfg = (name_or_cfg if isinstance(name_or_cfg, TransformerConfig)
           else get_config(name_or_cfg, **overrides))
    return PipelinedTransformer(cfg, pp=pp, n_micro=n_micro), cfg
