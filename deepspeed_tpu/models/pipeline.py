"""Pipelined flagship transformer — the model-side of SPMD pipeline parallelism.

Parameter structure is IDENTICAL to the non-pipelined scan-layers Transformer
(models/transformer.py) — wte/wpe/blocks[L,...]/ln_f — so checkpoints move
freely between pp=1 and pp=N topologies (the reference needs an offline
3D-reshape tool for this, deepspeed/checkpoint/; here it is true by
construction). The apply path differs: blocks are reshaped [L,...] ->
[pp, L/pp, ...] and executed with runtime/pipe/spmd.pipeline_apply; embedding
and head run replicated on every pipe rank (redundant compute, zero
communication — tied-embedding gradients need no ReduceTiedGrads step, unlike
the reference's tied-weight allreduce, pipe/engine.py _exec_reduce_tied_grads).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..runtime.pipe.spmd import (pipeline_apply, stack_stage_params,
                                 unstack_stage_params)
from .transformer import Block, Transformer, TransformerConfig

PyTree = Any


class PipelinedTransformer:
    """Engine-compatible model object (init/apply) that pipelines its blocks.

    n_micro: microbatches fed through the pipeline per train step (the
    reference's gradient_accumulation_steps == pipeline micro_batches,
    engine.py:  micro_batches = gas).
    """

    def __init__(self, cfg: TransformerConfig, pp: int, n_micro: int,
                 mesh=None):
        if cfg.num_layers % pp != 0:
            raise ValueError(f"num_layers {cfg.num_layers} not divisible by "
                             f"pp {pp}")
        if cfg.dropout != 0.0:
            raise NotImplementedError("pipelined path does not thread dropout "
                                      "rngs yet; set dropout=0")
        # MoE + PP: the MoE aux loss rides the pipe as a scalar side channel
        # next to the activations (spmd.pipeline_apply with_aux)
        self.cfg = cfg
        self.pp = pp
        self.n_micro = n_micro
        self.mesh = mesh
        # reference model for param init: identical param structure
        self._ref = Transformer(
            cfg if cfg.scan_layers else
            TransformerConfig(**{**cfg.__dict__, "scan_layers": True}))
        self._block = Block(cfg)
        self._ln_f = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                                  param_dtype=jnp.float32, name="ln_f")

    # -- engine model contract -----------------------------------------------

    def init(self, rng, batch, **kwargs):
        return self._ref.init(rng, batch, **kwargs)

    def apply(self, variables, batch, train: bool = False, rngs=None,
              mesh=None):
        params = variables["params"]
        cfg = self.cfg
        mesh = mesh or self.mesh
        if mesh is None:
            from ..parallel.mesh import get_global_mesh
            mesh = get_global_mesh().mesh
        if isinstance(batch, dict) and batch.get("attention_mask") is not None:
            raise NotImplementedError(
                "PipelinedTransformer does not thread attention_mask through "
                "the pipe loop yet; pad-free batches only (use pp=1 for "
                "masked batches)")
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        B, S = input_ids.shape
        if B % self.n_micro != 0:
            raise ValueError(f"batch {B} not divisible by n_micro {self.n_micro}")

        wte = params["wte"]["embedding"]            # [V, H] fp32
        wpe = params["wpe"]["embedding"]            # [T, H]
        # reshape the INTEGER ids to microbatches first: ids carry no
        # cotangent, so the data-axis reshard of the [B]->[n_micro, mb] split
        # never transposes into a low-precision collective (XLA SPMD miscompiles
        # bf16 resharding copies on some backends)
        ids_micros = input_ids.reshape(self.n_micro, B // self.n_micro, S)
        micros = (wte.astype(cfg.dtype)[ids_micros] +
                  wpe.astype(cfg.dtype)[jnp.arange(S)][None, None, :])
        stage_params = stack_stage_params(params["blocks"], self.pp)

        moe = cfg.moe_experts > 0

        def stage_fn(block_stack, h):
            # scan this stage's L/pp blocks (same compiled body per layer)
            def layer(carry, p):
                out, aux = self._block.apply({"params": p}, carry, None, train)
                return out, aux
            h, auxes = jax.lax.scan(layer, h, block_stack)
            if moe:
                return h, jnp.sum(auxes)
            return h

        res = pipeline_apply(stage_fn, stage_params, micros, mesh=mesh,
                             pp=self.pp, remat=cfg.remat, with_aux=moe)
        outs, aux_total = res if moe else (res, None)
        # head runs per-micro; only the fp32 logits are reshaped back to the
        # flat batch (fp32 resharding avoids the bf16 SPMD copy bug above)
        h = self._ln_f.apply({"params": params["ln_f"]}, outs)
        logits = jnp.einsum("nbsh,vh->nbsv", h,
                            wte.astype(cfg.dtype)).astype(jnp.float32)
        logits = logits.reshape((B, S, cfg.vocab_size))
        if moe:
            return logits, aux_total
        return logits

    __call__ = apply

    # -- 1F1B training path --------------------------------------------------

    def train_value_and_grad(self, params, batch, mesh=None):
        """Causal-LM loss + grads via the hand-scheduled 1F1B executor
        (runtime/pipe/one_f_one_b): activation memory ∝ pp (not n_micro) and
        the boundary stays bf16. Returns (loss, grads) with grads matching
        the params tree. MoE models use the GPipe path (the aux side channel
        is not threaded through the manual backward)."""
        cfg = self.cfg
        if cfg.moe_experts > 0:
            raise NotImplementedError("1F1B + MoE: use pipeline schedule "
                                      "'gpipe' for MoE models")
        mesh = mesh or self.mesh
        if mesh is None:
            from ..parallel.mesh import get_global_mesh
            mesh = get_global_mesh().mesh
        from ..runtime.pipe.one_f_one_b import pipeline_1f1b_value_and_grad
        if isinstance(batch, dict) and batch.get("attention_mask") is not None:
            raise NotImplementedError(
                "1F1B does not thread attention_mask; pad-free batches only")
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        labels = (batch.get("labels", input_ids) if isinstance(batch, dict)
                  else input_ids)
        B, S = input_ids.shape
        mb = B // self.n_micro
        ids_micros = input_ids.reshape(self.n_micro, mb, S)
        lab_micros = labels.reshape(self.n_micro, mb, S)

        def embed(wte, wpe):
            return (wte.astype(cfg.dtype)[ids_micros] +
                    wpe.astype(cfg.dtype)[jnp.arange(S)][None, None])

        micros, embed_vjp = jax.vjp(embed, params["wte"]["embedding"],
                                    params["wpe"]["embedding"])
        stage_params = stack_stage_params(params["blocks"], self.pp)

        def stage_fn(block_stack, h):
            def layer(carry, p):
                out, _ = self._block.apply({"params": p}, carry, None, False)
                return out, None
            h, _ = jax.lax.scan(layer, h, block_stack)
            return h

        head = {"ln_f": params["ln_f"], "wte": params["wte"]["embedding"]}
        # global token mean: the executor averages per-micro losses, so each
        # micro contributes its nll SUM scaled by n_micro/total_valid — with
        # unevenly -100-masked micros a per-micro mean would overweight
        # sparse ones vs the gpipe/causal_lm_loss objective
        total_valid = jnp.maximum(
            jnp.sum((lab_micros[:, :, 1:] != -100).astype(jnp.float32)), 1.0)

        def loss_fn(head_p, y, lab):
            h = self._ln_f.apply({"params": head_p["ln_f"]}, y)
            logits = jnp.einsum("bsh,vh->bsv", h,
                                head_p["wte"].astype(h.dtype))
            logits = logits[:, :-1].astype(jnp.float32)
            tgt = lab[:, 1:]
            valid = tgt != -100
            safe = jnp.where(valid, tgt, 0)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, safe[..., None],
                                       axis=-1)[..., 0]
            nll_sum = jnp.sum((logz - gold) * valid)
            return nll_sum * (self.n_micro / total_valid)

        loss, gs, gh, dmicros = pipeline_1f1b_value_and_grad(
            stage_fn, loss_fn, stage_params, head, micros, lab_micros,
            mesh=mesh, pp=self.pp)
        dwte_embed, dwpe = embed_vjp(dmicros)
        grads = {
            "wte": {"embedding": dwte_embed + gh["wte"]},
            "wpe": {"embedding": dwpe},
            "blocks": unstack_stage_params(gs),
            "ln_f": gh["ln_f"],
        }
        return loss, grads

    # -- sharding rules ------------------------------------------------------

    def tp_rules(self) -> Dict[str, P]:
        """Blocks lead with the 'pipe' axis on the layer dim; embed/head as in
        the non-pipelined rules."""
        def block(*spec):
            return P(*(("pipe",) + spec))

        return {
            r"blocks/.*attn_qkv/kernel": block(None, "model"),
            r"blocks/.*attn_qkv/bias": block("model"),
            r"blocks/.*attn_proj/kernel": block("model", None),
            r"blocks/.*mlp_fc/kernel": block(None, "model"),
            r"blocks/.*mlp_fc/bias": block("model"),
            r"blocks/.*mlp_proj/kernel": block("model", None),
            r"blocks/": P("pipe"),           # ln scales/biases: pipe only
            r"wte/embedding": P("model", None),
            r"lm_head/kernel": P(None, "model"),
        }


def build_pipelined_model(name_or_cfg, pp: int, n_micro: int, **overrides):
    from .transformer import get_config
    cfg = (name_or_cfg if isinstance(name_or_cfg, TransformerConfig)
           else get_config(name_or_cfg, **overrides))
    return PipelinedTransformer(cfg, pp=pp, n_micro=n_micro), cfg
