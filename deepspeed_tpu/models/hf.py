"""HuggingFace weight import — the policy-based module-substitution surface.

Capability parity with the reference's ``deepspeed/module_inject``
(replace_policy.py per-arch weight-name policies + containers/* weight-name
mapping). The reference walks a live torch model and rewires its layers to
fused CUDA modules; here the model IS the TPU-native Transformer, so a
"policy" is a weight-name mapping from a HF state dict into our params
pytree. TP slicing happens downstream via sharding rules (the reference
slices 1/tp_size by hand, containers/base.py:243).

Policies implemented: GPT-2 (HFGPT2Policy). The reference ships ~10
(replace_policy.py:18-32); further arches land as mappings here.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from .transformer import TransformerConfig

PyTree = Any


def _np(t):
    # torch tensor / numpy array -> numpy
    return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t)


def load_hf_gpt2(model_or_state_dict,
                 config=None) -> Tuple[PyTree, TransformerConfig]:
    """Convert a HF GPT2LMHeadModel (or its state_dict) to (params, cfg).

    HF Conv1D stores weights [in, out] — identical to the flax Dense kernel
    layout, so kernels map without transposition. Layout produced is the
    scan-layers one (blocks leaves [L, ...]).
    """
    if hasattr(model_or_state_dict, "state_dict"):
        sd = model_or_state_dict.state_dict()
        config = config or model_or_state_dict.config
    else:
        sd = dict(model_or_state_dict)
    if config is None:
        raise ValueError("pass the HF config when giving a raw state_dict")

    prefix = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    g = lambda name: _np(sd[prefix + name])

    L = config.n_layer
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.n_positions,
        hidden_size=config.n_embd,
        num_layers=L,
        num_heads=config.n_head,
        tie_embeddings=True,
        scan_layers=True,
        layer_norm_eps=float(config.layer_norm_epsilon),
    )

    def stack(name):
        return np.stack([g(f"h.{i}.{name}") for i in range(L)])

    blocks = {
        "ln1": {"scale": stack("ln_1.weight"), "bias": stack("ln_1.bias")},
        "attn_qkv": {"kernel": stack("attn.c_attn.weight"),
                     "bias": stack("attn.c_attn.bias")},
        "attn_proj": {"kernel": stack("attn.c_proj.weight"),
                      "bias": stack("attn.c_proj.bias")},
        "ln2": {"scale": stack("ln_2.weight"), "bias": stack("ln_2.bias")},
        "mlp_fc": {"kernel": stack("mlp.c_fc.weight"),
                   "bias": stack("mlp.c_fc.bias")},
        "mlp_proj": {"kernel": stack("mlp.c_proj.weight"),
                     "bias": stack("mlp.c_proj.bias")},
    }
    import jax
    params = jax.tree.map(
        lambda a: jnp.asarray(a, jnp.float32),
        {
            "wte": {"embedding": g("wte.weight")},
            "wpe": {"embedding": g("wpe.weight")},
            "blocks": blocks,
            "ln_f": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        })
    return params, cfg


# policy registry (reference: replace_policy.py replace_policies list)
HF_POLICIES = {
    "gpt2": load_hf_gpt2,
    "GPT2LMHeadModel": load_hf_gpt2,
}


def load_hf(model, arch: str = None):
    """Dispatch on HF architecture name (reference: replace_module.py policy
    matching by class)."""
    arch = arch or type(model).__name__
    for key, fn in HF_POLICIES.items():
        if key.lower() in arch.lower():
            return fn(model)
    raise NotImplementedError(
        f"no import policy for architecture '{arch}'; have {list(HF_POLICIES)}")
