"""HuggingFace weight import — the policy-based module-substitution surface.

Capability parity with the reference's ``deepspeed/module_inject``
(replace_policy.py per-arch weight-name policies + containers/* weight-name
mapping). The reference walks a live torch model and rewires its layers to
fused CUDA modules; here the model IS the TPU-native Transformer, so a
"policy" is a weight-name mapping from a HF state dict into our params
pytree. TP slicing happens downstream via sharding rules (the reference
slices 1/tp_size by hand, containers/base.py:243).

Policies implemented: GPT-2, GPT-Neo, GPT-NeoX, GPT-J, OPT, BLOOM, BERT,
RoBERTa, DistilBERT, CLIP-text, Megatron-GPT — 11 arches covering the
reference's replace_policy.py:18-32 list — plus the modern-decoder family
(EXCEEDS the reference, whose v0.8.1 policy list pre-dates them): Llama,
Mistral, Qwen2, Qwen3, Falcon (7B/40B/RW), GPT-BigCode/StarCoder, Phi,
Gemma, Gemma-2, and Mixtral — RMSNorm + SwiGLU + grouped-query attention,
sliding windows, qkv biases, scaled RoPE, softcapping, MoE: 21 total.
torch Linear weights are [out, in] and transpose into flax kernels;
GPT-2's Conv1D is already [in, out].
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from .transformer import TransformerConfig

PyTree = Any


def _np(t):
    # torch tensor / numpy array -> numpy
    return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t)


def load_hf_gpt2(model_or_state_dict,
                 config=None) -> Tuple[PyTree, TransformerConfig]:
    """Convert a HF GPT2LMHeadModel (or its state_dict) to (params, cfg).

    HF Conv1D stores weights [in, out] — identical to the flax Dense kernel
    layout, so kernels map without transposition. Layout produced is the
    scan-layers one (blocks leaves [L, ...]).
    """
    if hasattr(model_or_state_dict, "state_dict"):
        sd = model_or_state_dict.state_dict()
        config = config or model_or_state_dict.config
    else:
        sd = dict(model_or_state_dict)
    if config is None:
        raise ValueError("pass the HF config when giving a raw state_dict")

    prefix = _prefix(sd, "transformer.")
    g = lambda name: _np(sd[prefix + name])

    L = config.n_layer
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.n_positions,
        hidden_size=config.n_embd,
        num_layers=L,
        num_heads=config.n_head,
        tie_embeddings=True,
        scan_layers=True,
        layer_norm_eps=float(config.layer_norm_epsilon),
    )

    _stk = _stacker(g, L)

    def stack(name):
        return _stk(lambda i: g(f"h.{i}.{name}"))

    blocks = {
        "ln1": {"scale": stack("ln_1.weight"), "bias": stack("ln_1.bias")},
        "attn_qkv": {"kernel": stack("attn.c_attn.weight"),
                     "bias": stack("attn.c_attn.bias")},
        "attn_proj": {"kernel": stack("attn.c_proj.weight"),
                      "bias": stack("attn.c_proj.bias")},
        "ln2": {"scale": stack("ln_2.weight"), "bias": stack("ln_2.bias")},
        "mlp_fc": {"kernel": stack("mlp.c_fc.weight"),
                   "bias": stack("mlp.c_fc.bias")},
        "mlp_proj": {"kernel": stack("mlp.c_proj.weight"),
                     "bias": stack("mlp.c_proj.bias")},
    }
    import jax
    params = jax.tree.map(
        lambda a: jnp.asarray(a, jnp.float32),
        {
            "wte": {"embedding": g("wte.weight")},
            "wpe": {"embedding": g("wpe.weight")},
            "blocks": blocks,
            "ln_f": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        })
    return params, cfg



def _prefix(sd, candidate: str) -> str:
    """Detect whether keys carry the wrapper prefix (model vs bare decoder)."""
    return candidate if any(k.startswith(candidate) for k in sd) else ""


def _stacker(g, L: int):
    """Per-layer getter -> stacked [L, ...] leaf."""
    return lambda fn: np.stack([fn(i) for i in range(L)])


def _concat_qkv_linear(g, fmt: str, names=("q", "k", "v")):
    """Separate torch Linear projections -> one [H, 3H] flax qkv kernel."""
    def kernel(i):
        return np.concatenate([g(fmt.format(i=i, p=p)).T for p in names],
                              axis=1)

    def bias(i):
        return np.concatenate([g(fmt.format(i=i, p=p).replace(
            ".weight", ".bias")) for p in names])

    return kernel, bias


def _sd_and_config(model_or_state_dict, config):
    if hasattr(model_or_state_dict, "state_dict"):
        return (dict(model_or_state_dict.state_dict()),
                config or model_or_state_dict.config)
    if config is None:
        raise ValueError("pass the HF config when giving a raw state_dict")
    return dict(model_or_state_dict), config


def load_hf_gpt_neo(model_or_state_dict, config=None):
    """GPT-Neo (HF GPTNeoForCausalLM): separate unbiased q/k/v torch Linears
    concat into our qkv kernel; unscaled attention (attn_scale=1.0);
    alternating global/local attention layers become layer_windows."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "transformer.")
    g = lambda n: _np(sd[prefix + n])
    L = config.num_layers
    # config.attention_layers: ["global", "local", ...] per layer
    windows = tuple(config.window_size if a == "local" else 0
                    for a in config.attention_layers)
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings,
        hidden_size=config.hidden_size,
        num_layers=L,
        num_heads=config.num_heads,
        mlp_ratio=(config.intermediate_size or 4 * config.hidden_size)
        // config.hidden_size,
        tie_embeddings=True,
        scan_layers=True,
        layer_norm_eps=float(config.layer_norm_epsilon),
        attn_scale=1.0,
        qkv_bias=False,
        layer_windows=windows if any(windows) else None,
    )

    def qkv(i):
        ws = [g(f"h.{i}.attn.attention.{p}_proj.weight").T
              for p in ("q", "k", "v")]
        return np.concatenate(ws, axis=1)                    # [H, 3H]

    stack = _stacker(g, L)

    blocks = {
        "ln1": {"scale": stack(lambda i: g(f"h.{i}.ln_1.weight")),
                "bias": stack(lambda i: g(f"h.{i}.ln_1.bias"))},
        "attn_qkv": {"kernel": stack(qkv)},
        "attn_proj": {"kernel": stack(
            lambda i: g(f"h.{i}.attn.attention.out_proj.weight").T),
            "bias": stack(lambda i: g(f"h.{i}.attn.attention.out_proj.bias"))},
        "ln2": {"scale": stack(lambda i: g(f"h.{i}.ln_2.weight")),
                "bias": stack(lambda i: g(f"h.{i}.ln_2.bias"))},
        "mlp_fc": {"kernel": stack(lambda i: g(f"h.{i}.mlp.c_fc.weight").T),
                   "bias": stack(lambda i: g(f"h.{i}.mlp.c_fc.bias"))},
        "mlp_proj": {"kernel": stack(lambda i: g(f"h.{i}.mlp.c_proj.weight").T),
                     "bias": stack(lambda i: g(f"h.{i}.mlp.c_proj.bias"))},
    }
    params = {
        "wte": {"embedding": g("wte.weight")},
        "wpe": {"embedding": g("wpe.weight")},
        "blocks": blocks,
        "ln_f": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }
    return _to_f32(params), cfg


def load_hf_gptj(model_or_state_dict, config=None):
    """GPT-J (HF GPTJForCausalLM): rotary positions, parallel attention+MLP
    residual off one shared LayerNorm, untied biased lm_head."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "transformer.")
    g = lambda n: _np(sd[prefix + n])
    L = config.n_layer
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.n_positions,
        hidden_size=config.n_embd,
        num_layers=L,
        num_heads=config.n_head,
        mlp_ratio=(getattr(config, "n_inner", None) or 4 * config.n_embd)
        // config.n_embd,
        tie_embeddings=False,
        lm_head_bias=True,
        scan_layers=True,
        layer_norm_eps=float(config.layer_norm_epsilon),
        pos_embed="rotary",
        rotary_dim=config.rotary_dim or 0,
        parallel_residual=True,
        qkv_bias=False,
        attn_out_bias=False,
    )

    def qkv(i):
        ws = [g(f"h.{i}.attn.{p}_proj.weight").T for p in ("q", "k", "v")]
        return np.concatenate(ws, axis=1)

    stack = _stacker(g, L)

    blocks = {
        "ln1": {"scale": stack(lambda i: g(f"h.{i}.ln_1.weight")),
                "bias": stack(lambda i: g(f"h.{i}.ln_1.bias"))},
        "attn_qkv": {"kernel": stack(qkv)},
        "attn_proj": {"kernel": stack(
            lambda i: g(f"h.{i}.attn.out_proj.weight").T)},
        "mlp_fc": {"kernel": stack(lambda i: g(f"h.{i}.mlp.fc_in.weight").T),
                   "bias": stack(lambda i: g(f"h.{i}.mlp.fc_in.bias"))},
        "mlp_proj": {"kernel": stack(lambda i: g(f"h.{i}.mlp.fc_out.weight").T),
                     "bias": stack(lambda i: g(f"h.{i}.mlp.fc_out.bias"))},
    }
    params = {
        "wte": {"embedding": g("wte.weight")},
        "blocks": blocks,
        "ln_f": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        "lm_head": {"kernel": _np(sd["lm_head.weight"]).T,
                    "bias": _np(sd["lm_head.bias"])},
    }
    return _to_f32(params), cfg


def load_hf_opt(model_or_state_dict, config=None):
    """OPT (HF OPTForCausalLM): pre-LN decoder with ReLU and learned
    positions at a +2 offset — the offset is baked by dropping the embedding
    table's first two rows."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "model.decoder.") or "decoder."
    g = lambda n: _np(sd[prefix + n])
    if not getattr(config, "do_layer_norm_before", True):
        raise NotImplementedError("OPT with do_layer_norm_before=False "
                                  "(350m variant) is post-LN; not mapped")
    if config.word_embed_proj_dim != config.hidden_size:
        raise NotImplementedError("OPT word_embed_proj_dim != hidden_size "
                                  "needs the projection layers")
    L = config.num_hidden_layers
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings,
        hidden_size=config.hidden_size,
        num_layers=L,
        num_heads=config.num_attention_heads,
        mlp_ratio=config.ffn_dim // config.hidden_size,
        tie_embeddings=True,
        scan_layers=True,
        layer_norm_eps=1e-5,
        activation="relu",
    )

    def qkv_w(i):
        ws = [g(f"layers.{i}.self_attn.{p}_proj.weight").T
              for p in ("q", "k", "v")]
        return np.concatenate(ws, axis=1)

    def qkv_b(i):
        bs = [g(f"layers.{i}.self_attn.{p}_proj.bias") for p in ("q", "k", "v")]
        return np.concatenate(bs)

    stack = _stacker(g, L)

    blocks = {
        "ln1": {"scale": stack(
            lambda i: g(f"layers.{i}.self_attn_layer_norm.weight")),
            "bias": stack(lambda i: g(f"layers.{i}.self_attn_layer_norm.bias"))},
        "attn_qkv": {"kernel": stack(qkv_w), "bias": stack(qkv_b)},
        "attn_proj": {"kernel": stack(
            lambda i: g(f"layers.{i}.self_attn.out_proj.weight").T),
            "bias": stack(lambda i: g(f"layers.{i}.self_attn.out_proj.bias"))},
        "ln2": {"scale": stack(lambda i: g(f"layers.{i}.final_layer_norm.weight")),
                "bias": stack(lambda i: g(f"layers.{i}.final_layer_norm.bias"))},
        "mlp_fc": {"kernel": stack(lambda i: g(f"layers.{i}.fc1.weight").T),
                   "bias": stack(lambda i: g(f"layers.{i}.fc1.bias"))},
        "mlp_proj": {"kernel": stack(lambda i: g(f"layers.{i}.fc2.weight").T),
                     "bias": stack(lambda i: g(f"layers.{i}.fc2.bias"))},
    }
    params = {
        "wte": {"embedding": g("embed_tokens.weight")},
        # OPTLearnedPositionalEmbedding adds +2 to every position index
        "wpe": {"embedding": g("embed_positions.weight")[2:]},
        "blocks": blocks,
        "ln_f": {"scale": g("final_layer_norm.weight"),
                 "bias": g("final_layer_norm.bias")},
    }
    return _to_f32(params), cfg


def load_hf_bloom(model_or_state_dict, config=None, max_seq_len=None):
    """BLOOM (HF BloomForCausalLM): ALiBi attention, LayerNorm on the word
    embeddings, fused qkv stored head-major ([nh, 3, hd] on the out dim) —
    permuted here into our contiguous q|k|v layout.

    ALiBi has no positional table, so max_seq_len is only a KV-cache sizing
    bound: defaults to the config's training length (seq_length, 2048 for
    released BLOOMs); pass max_seq_len to extrapolate longer."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "transformer.")
    g = lambda n: _np(sd[prefix + n])
    L = config.n_layer
    H = config.hidden_size
    nh = config.n_head
    hd = H // nh
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=max_seq_len or getattr(config, "seq_length", 2048),
        hidden_size=H,
        num_layers=L,
        num_heads=nh,
        mlp_ratio=4,
        tie_embeddings=True,
        scan_layers=True,
        layer_norm_eps=float(config.layer_norm_epsilon),
        pos_embed="alibi",
        embed_ln=True,
    )

    def qkv_w(i):
        w = g(f"h.{i}.self_attention.query_key_value.weight")  # [3H, H]
        w = w.reshape(nh, 3, hd, H).transpose(1, 0, 2, 3).reshape(3 * H, H)
        return w.T                                             # [H, 3H]

    def qkv_b(i):
        b = g(f"h.{i}.self_attention.query_key_value.bias")
        return b.reshape(nh, 3, hd).transpose(1, 0, 2).reshape(3 * H)

    stack = _stacker(g, L)

    blocks = {
        "ln1": {"scale": stack(lambda i: g(f"h.{i}.input_layernorm.weight")),
                "bias": stack(lambda i: g(f"h.{i}.input_layernorm.bias"))},
        "attn_qkv": {"kernel": stack(qkv_w), "bias": stack(qkv_b)},
        "attn_proj": {"kernel": stack(
            lambda i: g(f"h.{i}.self_attention.dense.weight").T),
            "bias": stack(lambda i: g(f"h.{i}.self_attention.dense.bias"))},
        "ln2": {"scale": stack(
            lambda i: g(f"h.{i}.post_attention_layernorm.weight")),
            "bias": stack(lambda i: g(f"h.{i}.post_attention_layernorm.bias"))},
        "mlp_fc": {"kernel": stack(
            lambda i: g(f"h.{i}.mlp.dense_h_to_4h.weight").T),
            "bias": stack(lambda i: g(f"h.{i}.mlp.dense_h_to_4h.bias"))},
        "mlp_proj": {"kernel": stack(
            lambda i: g(f"h.{i}.mlp.dense_4h_to_h.weight").T),
            "bias": stack(lambda i: g(f"h.{i}.mlp.dense_4h_to_h.bias"))},
    }
    params = {
        "wte": {"embedding": g("word_embeddings.weight")},
        "ln_emb": {"scale": g("word_embeddings_layernorm.weight"),
                   "bias": g("word_embeddings_layernorm.bias")},
        "blocks": blocks,
        "ln_f": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }
    return _to_f32(params), cfg



def _bert_encoder_blocks(g, L: int, enc: str = "encoder.layer."):
    """BERT-family encoder mapping shared by the BERT and RoBERTa loaders
    (identical HF key names and layouts)."""
    qkv_w, qkv_b = _concat_qkv_linear(
        g, enc + "{i}.attention.self.{p}.weight",
        names=("query", "key", "value"))
    stack = _stacker(g, L)
    return {
        "attn_qkv": {"kernel": stack(qkv_w), "bias": stack(qkv_b)},
        "attn_proj": {"kernel": stack(
            lambda i: g(f"{enc}{i}.attention.output.dense.weight").T),
            "bias": stack(lambda i: g(f"{enc}{i}.attention.output.dense.bias"))},
        "ln1": {"scale": stack(
            lambda i: g(f"{enc}{i}.attention.output.LayerNorm.weight")),
            "bias": stack(
                lambda i: g(f"{enc}{i}.attention.output.LayerNorm.bias"))},
        "mlp_fc": {"kernel": stack(
            lambda i: g(f"{enc}{i}.intermediate.dense.weight").T),
            "bias": stack(lambda i: g(f"{enc}{i}.intermediate.dense.bias"))},
        "mlp_proj": {"kernel": stack(
            lambda i: g(f"{enc}{i}.output.dense.weight").T),
            "bias": stack(lambda i: g(f"{enc}{i}.output.dense.bias"))},
        "ln2": {"scale": stack(lambda i: g(f"{enc}{i}.output.LayerNorm.weight")),
                "bias": stack(lambda i: g(f"{enc}{i}.output.LayerNorm.bias"))},
    }


def load_hf_bert(model_or_state_dict, config=None):
    """BERT (HF BertForMaskedLM): post-LN encoder with token-type embeddings
    and the MLM prediction head (transform + tied decoder + bias)."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "bert.")
    g = lambda n: _np(sd[prefix + n])
    L = config.num_hidden_layers
    act = {"gelu": "gelu_exact", "gelu_new": "gelu", "relu": "relu"}[
        config.hidden_act]
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings,
        hidden_size=config.hidden_size,
        num_layers=L,
        num_heads=config.num_attention_heads,
        mlp_ratio=config.intermediate_size // config.hidden_size,
        causal=False,
        tie_embeddings=True,
        scan_layers=True,
        layer_norm_eps=float(config.layer_norm_eps),
        activation=act,
        post_ln=True,
        embed_ln=True,
        token_type_vocab=config.type_vocab_size,
        mlm_head=True,
    )
    blocks = _bert_encoder_blocks(g, L)
    params = {
        "wte": {"embedding": g("embeddings.word_embeddings.weight")},
        "wpe": {"embedding": g("embeddings.position_embeddings.weight")},
        "tte": {"embedding": g("embeddings.token_type_embeddings.weight")},
        "ln_emb": {"scale": g("embeddings.LayerNorm.weight"),
                   "bias": g("embeddings.LayerNorm.bias")},
        "blocks": blocks,
        "mlm_transform": {
            "kernel": _np(sd["cls.predictions.transform.dense.weight"]).T,
            "bias": _np(sd["cls.predictions.transform.dense.bias"])},
        "mlm_ln": {"scale": _np(sd["cls.predictions.transform.LayerNorm.weight"]),
                   "bias": _np(sd["cls.predictions.transform.LayerNorm.bias"])},
        "mlm_bias": _np(sd["cls.predictions.bias"]),
    }
    return _to_f32(params), cfg


def load_hf_roberta(model_or_state_dict, config=None):
    """RoBERTa (HF RobertaForMaskedLM): BERT's post-LN encoder with position
    ids offset by padding_idx+1 (baked by dropping the first rows) and the
    lm_head transform instead of cls.predictions."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "roberta.")
    g = lambda n: _np(sd[prefix + n])
    L = config.num_hidden_layers
    offset = config.pad_token_id + 1          # RoBERTa position offset
    act = {"gelu": "gelu_exact", "gelu_new": "gelu", "relu": "relu"}[
        config.hidden_act]
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings - offset,
        hidden_size=config.hidden_size,
        num_layers=L,
        num_heads=config.num_attention_heads,
        mlp_ratio=config.intermediate_size // config.hidden_size,
        causal=False,
        tie_embeddings=True,
        scan_layers=True,
        layer_norm_eps=float(config.layer_norm_eps),
        activation=act,
        post_ln=True,
        embed_ln=True,
        token_type_vocab=config.type_vocab_size,
        mlm_head=True,
    )
    blocks = _bert_encoder_blocks(g, L)
    params = {
        "wte": {"embedding": g("embeddings.word_embeddings.weight")},
        "wpe": {"embedding": g("embeddings.position_embeddings.weight")[offset:]},
        "tte": {"embedding": g("embeddings.token_type_embeddings.weight")},
        "ln_emb": {"scale": g("embeddings.LayerNorm.weight"),
                   "bias": g("embeddings.LayerNorm.bias")},
        "blocks": blocks,
        "mlm_transform": {"kernel": _np(sd["lm_head.dense.weight"]).T,
                          "bias": _np(sd["lm_head.dense.bias"])},
        "mlm_ln": {"scale": _np(sd["lm_head.layer_norm.weight"]),
                   "bias": _np(sd["lm_head.layer_norm.bias"])},
        "mlm_bias": _np(sd["lm_head.bias"]),
    }
    return _to_f32(params), cfg


def load_hf_distilbert(model_or_state_dict, config=None):
    """DistilBERT (HF DistilBertForMaskedLM): BERT-style post-LN encoder,
    no token-type embeddings, vocab_transform/vocab_projector MLM head."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "distilbert.")
    g = lambda n: _np(sd[prefix + n])
    L = config.n_layers
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings,
        hidden_size=config.dim,
        num_layers=L,
        num_heads=config.n_heads,
        mlp_ratio=config.hidden_dim // config.dim,
        causal=False,
        tie_embeddings=True,
        scan_layers=True,
        layer_norm_eps=1e-12,
        activation="gelu_exact" if config.activation == "gelu" else "relu",
        post_ln=True,
        embed_ln=True,
        mlm_head=True,
    )
    lyr = "transformer.layer."
    qkv_w, qkv_b = _concat_qkv_linear(
        g, lyr + "{i}.attention.{p}_lin.weight", names=("q", "k", "v"))
    stack = _stacker(g, L)
    blocks = {
        "attn_qkv": {"kernel": stack(qkv_w), "bias": stack(qkv_b)},
        "attn_proj": {"kernel": stack(
            lambda i: g(f"{lyr}{i}.attention.out_lin.weight").T),
            "bias": stack(lambda i: g(f"{lyr}{i}.attention.out_lin.bias"))},
        "ln1": {"scale": stack(lambda i: g(f"{lyr}{i}.sa_layer_norm.weight")),
                "bias": stack(lambda i: g(f"{lyr}{i}.sa_layer_norm.bias"))},
        "mlp_fc": {"kernel": stack(lambda i: g(f"{lyr}{i}.ffn.lin1.weight").T),
                   "bias": stack(lambda i: g(f"{lyr}{i}.ffn.lin1.bias"))},
        "mlp_proj": {"kernel": stack(lambda i: g(f"{lyr}{i}.ffn.lin2.weight").T),
                     "bias": stack(lambda i: g(f"{lyr}{i}.ffn.lin2.bias"))},
        "ln2": {"scale": stack(
            lambda i: g(f"{lyr}{i}.output_layer_norm.weight")),
            "bias": stack(lambda i: g(f"{lyr}{i}.output_layer_norm.bias"))},
    }
    params = {
        "wte": {"embedding": g("embeddings.word_embeddings.weight")},
        "wpe": {"embedding": g("embeddings.position_embeddings.weight")},
        "ln_emb": {"scale": g("embeddings.LayerNorm.weight"),
                   "bias": g("embeddings.LayerNorm.bias")},
        "blocks": blocks,
        "mlm_transform": {"kernel": _np(sd["vocab_transform.weight"]).T,
                          "bias": _np(sd["vocab_transform.bias"])},
        "mlm_ln": {"scale": _np(sd["vocab_layer_norm.weight"]),
                   "bias": _np(sd["vocab_layer_norm.bias"])},
        "mlm_bias": _np(sd["vocab_projector.bias"]),
    }
    return _to_f32(params), cfg


def _deinterleave_qkv(w, b, nh: int, hd: int):
    """Per-head-interleaved fused qkv ([nh, 3, hd] out-rows, GPT-NeoX /
    Megatron v2+) -> our [H, 3H] kernel with q/k/v column groups."""
    H = nh * hd
    wr = w.reshape(nh, 3, hd, H)
    kernel = np.concatenate(
        [wr[:, j].reshape(H, H).T for j in range(3)], axis=1)    # [H, 3H]
    bias = None
    if b is not None:
        br = b.reshape(nh, 3, hd)
        bias = np.concatenate([br[:, j].reshape(H) for j in range(3)])
    return kernel, bias


def load_hf_gpt_neox(model_or_state_dict, config=None):
    """GPT-NeoX (HF GPTNeoXForCausalLM, e.g. Pythia): dual-LayerNorm parallel
    residual (x + attn(ln1 x) + mlp(ln2 x)), rotate_half rotary over
    rotary_pct of head_dim, per-head-interleaved fused qkv, untied unbiased
    embed_out. reference arch coverage: module_inject GPT-NeoX policy."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "gpt_neox.")
    g = lambda n: _np(sd[prefix + n])
    L = config.num_hidden_layers
    nh = config.num_attention_heads
    H = config.hidden_size
    hd = H // nh
    parallel = bool(getattr(config, "use_parallel_residual", True))
    base = float(getattr(config, "rotary_emb_base", 10000.0))
    cfg = TransformerConfig(
        rope_theta=base,
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings,
        hidden_size=H,
        num_layers=L,
        num_heads=nh,
        mlp_ratio=config.intermediate_size // H,
        tie_embeddings=False,
        scan_layers=True,
        layer_norm_eps=float(config.layer_norm_eps),
        pos_embed="rotary",
        rotary_dim=int(hd * config.rotary_pct),
        rotary_interleaved=False,
        parallel_residual=parallel,
        parallel_residual_dual_ln=parallel,
        # HF ACT2FN["gelu"] is exact-erf (the NeoX default); our "gelu" is
        # the tanh approximation — map strictly like the BERT/RoBERTa
        # loaders so unknown activations fail at load time, not in apply
        activation={"gelu": "gelu_exact", "gelu_new": "gelu",
                    "gelu_pytorch_tanh": "gelu", "relu": "relu",
                    "quick_gelu": "quick_gelu"}[
            getattr(config, "hidden_act", "gelu")],
    )

    qkv_ws, qkv_bs = zip(*[_deinterleave_qkv(
        g(f"layers.{i}.attention.query_key_value.weight"),
        g(f"layers.{i}.attention.query_key_value.bias"), nh, hd)
        for i in range(L)])

    stack = _stacker(g, L)
    blocks = {
        "ln1": {"scale": stack(lambda i: g(f"layers.{i}.input_layernorm.weight")),
                "bias": stack(lambda i: g(f"layers.{i}.input_layernorm.bias"))},
        "ln2": {"scale": stack(
            lambda i: g(f"layers.{i}.post_attention_layernorm.weight")),
            "bias": stack(
            lambda i: g(f"layers.{i}.post_attention_layernorm.bias"))},
        "attn_qkv": {"kernel": np.stack(qkv_ws), "bias": np.stack(qkv_bs)},
        "attn_proj": {"kernel": stack(
            lambda i: g(f"layers.{i}.attention.dense.weight").T),
            "bias": stack(lambda i: g(f"layers.{i}.attention.dense.bias"))},
        "mlp_fc": {"kernel": stack(
            lambda i: g(f"layers.{i}.mlp.dense_h_to_4h.weight").T),
            "bias": stack(lambda i: g(f"layers.{i}.mlp.dense_h_to_4h.bias"))},
        "mlp_proj": {"kernel": stack(
            lambda i: g(f"layers.{i}.mlp.dense_4h_to_h.weight").T),
            "bias": stack(lambda i: g(f"layers.{i}.mlp.dense_4h_to_h.bias"))},
    }
    params = {
        "wte": {"embedding": g("embed_in.weight")},
        "blocks": blocks,
        "ln_f": {"scale": g("final_layer_norm.weight"),
                 "bias": g("final_layer_norm.bias")},
        "lm_head": {"kernel": _np(sd["embed_out.weight"]).T},
    }
    return _to_f32(params), cfg


def load_hf_clip_text(model_or_state_dict, config=None):
    """CLIP text encoder (HF CLIPTextModel): causal pre-LN stack with
    quick_gelu and no LM head — the output is the final hidden states
    (reference: module_inject CLIP policy / diffusers generic_injection)."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    if hasattr(config, "text_config"):      # full CLIPConfig passed
        config = config.text_config
    prefix = _prefix(sd, "text_model.")
    g = lambda n: _np(sd[prefix + n])
    L = config.num_hidden_layers
    H = config.hidden_size
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings,
        hidden_size=H,
        num_layers=L,
        num_heads=config.num_attention_heads,
        mlp_ratio=config.intermediate_size // H,
        tie_embeddings=False,
        no_lm_head=True,
        scan_layers=True,
        layer_norm_eps=float(config.layer_norm_eps),
        activation={"quick_gelu": "quick_gelu", "gelu": "gelu_exact"}.get(
            config.hidden_act, config.hidden_act),
        causal=True,
    )
    fmt = "encoder.layers.{i}.self_attn.{p}_proj.weight"
    qkv_kernel, qkv_bias = _concat_qkv_linear(g, fmt)
    stack = _stacker(g, L)
    blocks = {
        "ln1": {"scale": stack(
            lambda i: g(f"encoder.layers.{i}.layer_norm1.weight")),
            "bias": stack(lambda i: g(f"encoder.layers.{i}.layer_norm1.bias"))},
        "attn_qkv": {"kernel": stack(qkv_kernel), "bias": stack(qkv_bias)},
        "attn_proj": {"kernel": stack(
            lambda i: g(f"encoder.layers.{i}.self_attn.out_proj.weight").T),
            "bias": stack(
            lambda i: g(f"encoder.layers.{i}.self_attn.out_proj.bias"))},
        "ln2": {"scale": stack(
            lambda i: g(f"encoder.layers.{i}.layer_norm2.weight")),
            "bias": stack(lambda i: g(f"encoder.layers.{i}.layer_norm2.bias"))},
        "mlp_fc": {"kernel": stack(
            lambda i: g(f"encoder.layers.{i}.mlp.fc1.weight").T),
            "bias": stack(lambda i: g(f"encoder.layers.{i}.mlp.fc1.bias"))},
        "mlp_proj": {"kernel": stack(
            lambda i: g(f"encoder.layers.{i}.mlp.fc2.weight").T),
            "bias": stack(lambda i: g(f"encoder.layers.{i}.mlp.fc2.bias"))},
    }
    params = {
        "wte": {"embedding": g("embeddings.token_embedding.weight")},
        "wpe": {"embedding": g("embeddings.position_embedding.weight")},
        "blocks": blocks,
        "ln_f": {"scale": g("final_layer_norm.weight"),
                 "bias": g("final_layer_norm.bias")},
    }
    return _to_f32(params), cfg


def load_megatron_gpt(state_dict, config, version: int = 2):
    """Megatron-LM GPT (NVIDIA checkpoint 'model' dict): pre-LN GPT-2-shaped
    stack under language_model.{embedding,transformer|encoder} keys with a
    fused query_key_value whose row layout depends on checkpoint version —
    >=2: per-head [q|k|v] interleaved; 0: q/k/v chunked. Tied embeddings.
    (reference: module_inject megatron policy + its container's
    megatron-version split.) `config` needs num_layers/hidden_size/num_heads/
    vocab_size/max_seq_len (dict or any attr object)."""
    get = (config.get if isinstance(config, dict)
           else lambda k, d=None: getattr(config, k, d))
    L, H = get("num_layers"), get("hidden_size")
    nh = get("num_heads")
    hd = H // nh
    sd = dict(state_dict)
    lm = _prefix(sd, "language_model.")
    enc = "transformer." if any(
        k.startswith(f"{lm}transformer.") for k in sd) else "encoder."
    g = lambda n: _np(sd[lm + n])
    ge = lambda n: g(enc + n)
    cfg = TransformerConfig(
        vocab_size=get("vocab_size"),
        max_seq_len=get("max_seq_len", 1024),
        hidden_size=H, num_layers=L, num_heads=nh,
        mlp_ratio=get("mlp_ratio", 4),
        tie_embeddings=True, scan_layers=True,
        layer_norm_eps=float(get("layer_norm_eps", 1e-5)),
    )

    def qkv(i):
        w = ge(f"layers.{i}.attention.query_key_value.weight")
        b = ge(f"layers.{i}.attention.query_key_value.bias")
        if version >= 2:
            return _deinterleave_qkv(w, b, nh, hd)
        return w.T, b                              # chunked: already [q|k|v]

    qkv_ws, qkv_bs = zip(*[qkv(i) for i in range(L)])
    stack = _stacker(g, L)
    blocks = {
        "ln1": {"scale": stack(lambda i: ge(f"layers.{i}.input_layernorm.weight")),
                "bias": stack(lambda i: ge(f"layers.{i}.input_layernorm.bias"))},
        "attn_qkv": {"kernel": np.stack(qkv_ws), "bias": np.stack(qkv_bs)},
        "attn_proj": {"kernel": stack(
            lambda i: ge(f"layers.{i}.attention.dense.weight").T),
            "bias": stack(lambda i: ge(f"layers.{i}.attention.dense.bias"))},
        "ln2": {"scale": stack(
            lambda i: ge(f"layers.{i}.post_attention_layernorm.weight")),
            "bias": stack(
            lambda i: ge(f"layers.{i}.post_attention_layernorm.bias"))},
        "mlp_fc": {"kernel": stack(
            lambda i: ge(f"layers.{i}.mlp.dense_h_to_4h.weight").T),
            "bias": stack(lambda i: ge(f"layers.{i}.mlp.dense_h_to_4h.bias"))},
        "mlp_proj": {"kernel": stack(
            lambda i: ge(f"layers.{i}.mlp.dense_4h_to_h.weight").T),
            "bias": stack(lambda i: ge(f"layers.{i}.mlp.dense_4h_to_h.bias"))},
    }
    params = {
        "wte": {"embedding": g("embedding.word_embeddings.weight")},
        "wpe": {"embedding": g("embedding.position_embeddings.weight")},
        "blocks": blocks,
        "ln_f": {"scale": ge("final_layernorm.weight"),
                 "bias": ge("final_layernorm.bias")},
    }
    return _to_f32(params), cfg


def _to_f32(params):
    import jax
    return jax.tree.map(lambda a: jnp.asarray(a, jnp.float32), params)


# policy registry (reference: replace_policy.py replace_policies list)
def _llama_family_params(sd, prefix, L, qkv_bias=False, o_bias=False,
                         mlp_bias=False, qk_norm=False, moe_experts=0,
                         norm_plus_one=False, sandwich_norms=False):
    """Shared Llama/Mistral/Qwen2/Qwen3/Mixtral block mapping: RMSNorm +
    GQA qkv + SwiGLU (dense, or ``moe_experts`` SwiGLU experts behind a
    router — HF block_sparse_moe w1/w3/w2 -> our moe.experts
    gate/fc/proj). Bias flags are PRESENCE-driven by the caller (Llama
    attention_bias has q/k/v/o biases; Qwen2 has q/k/v only; mlp_bias
    biases gate/up/down; qk_norm adds Qwen3's per-head q/k RMSNorm)."""
    g = lambda n: _np(sd[prefix + n])
    stack = _stacker(g, L)
    # Gemma stores RMSNorm weights as w with the forward computing
    # x * (1 + w); folding the +1 into the stored scale makes the standard
    # scale-multiply RMSNorm bit-equivalent — the fold happens in f32
    # (like HF's `1.0 + weight.float()`), not the checkpoint's storage
    # dtype, so fp16/bf16 state dicts don't round (1+w) prematurely
    ln_w = ((lambda a: np.asarray(a, np.float32) + 1.0) if norm_plus_one
            else (lambda a: a))

    def qkv(i):
        ws = [g(f"layers.{i}.self_attn.{p}_proj.weight").T
              for p in ("q", "k", "v")]
        return np.concatenate(ws, axis=1)     # [H, (nh + 2*kv) * hd]

    def qkv_b(i):
        return np.concatenate(
            [g(f"layers.{i}.self_attn.{p}_proj.bias") for p in ("q", "k", "v")])

    def proj(hf, biased):
        p = {"kernel": stack(lambda i: g(f"layers.{i}.{hf}.weight").T)}
        if biased:
            p["bias"] = stack(lambda i: g(f"layers.{i}.{hf}.bias"))
        return p

    # Gemma-2 sandwich layout: post_attention_layernorm is the POST-attn
    # branch norm and pre_feedforward_layernorm takes the pre-MLP (ln2)
    # slot; everyone else's post_attention_layernorm IS the pre-MLP norm
    ln2_src = ("pre_feedforward_layernorm" if sandwich_norms
               else "post_attention_layernorm")
    blocks = {
        "ln1": {"scale": stack(
            lambda i: ln_w(g(f"layers.{i}.input_layernorm.weight")))},
        "attn_qkv": ({"kernel": stack(qkv), "bias": stack(qkv_b)}
                     if qkv_bias else {"kernel": stack(qkv)}),
        "attn_proj": proj("self_attn.o_proj", o_bias),
        "ln2": {"scale": stack(
            lambda i: ln_w(g(f"layers.{i}.{ln2_src}.weight")))},
    }
    if sandwich_norms:
        for ours, hfn in (("post_attn_norm", "post_attention_layernorm"),
                          ("post_mlp_norm", "post_feedforward_layernorm")):
            blocks[ours] = {"scale": stack(
                lambda i, n=hfn: ln_w(g(f"layers.{i}.{n}.weight")))}
    if moe_experts > 0:
        E = moe_experts

        def estack(w):
            """[L, E, in, out] expert-stacked kernels (HF stores [out, in])."""
            return stack(lambda i: np.stack(
                [g(f"layers.{i}.block_sparse_moe.experts.{j}.{w}.weight").T
                 for j in range(E)]))

        blocks["moe"] = {
            "gate": {"kernel": stack(
                lambda i: g(f"layers.{i}.block_sparse_moe.gate.weight").T)},
            # HF MixtralBlockSparseTop2MLP: w1 = gate, w3 = up, w2 = down
            "experts": {"gate": {"kernel": estack("w1")},
                        "fc": {"kernel": estack("w3")},
                        "proj": {"kernel": estack("w2")}},
        }
    else:
        blocks.update(
            mlp_gate=proj("mlp.gate_proj", mlp_bias),
            mlp_fc=proj("mlp.up_proj", mlp_bias),
            mlp_proj=proj("mlp.down_proj", mlp_bias),
        )
    if qk_norm:
        for name in ("q_norm", "k_norm"):
            blocks[name] = {"scale": stack(
                lambda i, n=name: g(f"layers.{i}.self_attn.{n}.weight"))}
    params = {
        "wte": {"embedding": g("embed_tokens.weight")},
        "blocks": blocks,
        "ln_f": {"scale": ln_w(g("norm.weight"))},
    }
    return params, g


def _load_hf_llama_family(model_or_state_dict, config,
                          use_sliding_window=False, moe=False,
                          activation="silu", embed_scale=None,
                          norm_plus_one=False, gemma2=False):
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "model.")
    L = config.num_hidden_layers
    moe_experts = int(getattr(config, "num_local_experts", 0)) if moe else 0
    moe_k = int(getattr(config, "num_experts_per_tok", 2)) if moe else 1
    windows = None
    if use_sliding_window:
        w = getattr(config, "sliding_window", None)
        if use_sliding_window == "qwen2":
            # Qwen2 gates the window behind use_sliding_window and leaves
            # the first max_window_layers on full attention
            if getattr(config, "use_sliding_window", False) and w:
                mw = int(getattr(config, "max_window_layers", 0))
                windows = tuple(0 if i < mw else int(w) for i in range(L))
        elif use_sliding_window == "layer_types":
            # Qwen3: per-layer attention kind in config.layer_types
            lt = getattr(config, "layer_types", None)
            if w and lt:
                windows = tuple(int(w) if t == "sliding_attention" else 0
                                for t in lt)
        elif w:                                  # Mistral: every layer
            windows = (int(w),) * L
    kv = getattr(config, "num_key_value_heads", None) \
        or config.num_attention_heads
    tie = bool(getattr(config, "tie_word_embeddings", False))
    # scaled RoPE (Llama-3.1+ / linear PI / dynamic NTK): mapped onto the
    # static rope_scaling_* config knobs (TransformerConfig.rope_inv_freq
    # mirrors HF modeling_rope_utils token-exactly). Genuinely unsupported
    # geometries (yarn / longrope) still fail HERE, not decode garbage.
    scaling = getattr(config, "rope_scaling", None) or {}
    rope_type = scaling.get("rope_type", scaling.get("type", "default"))
    if rope_type not in ("default", "linear", "dynamic", "llama3"):
        raise NotImplementedError(
            f"rope_scaling type {rope_type!r} is not implemented "
            "(yarn / longrope): loading with plain rope_theta would "
            "produce wrong frequencies")
    rope_kwargs = {}
    if rope_type != "default":
        # "factor" is mandatory for every scaled type (HF raises KeyError
        # in modeling_rope_utils too) — a missing key must not quietly
        # load as an unscaled table
        rope_kwargs = dict(
            rope_scaling_type=rope_type,
            rope_scaling_factor=float(scaling["factor"]),
            # dynamic NTK: HF ignores the dict's
            # original_max_position_embeddings (explicit TODO there) and
            # stretches relative to config.max_position_embeddings;
            # llama3 reads the dict key. Mirror each exactly.
            rope_original_max_position=int(
                config.max_position_embeddings if rope_type != "llama3"
                else scaling.get("original_max_position_embeddings",
                                 config.max_position_embeddings)),
        )
        if rope_type == "llama3":
            rope_kwargs.update(
                rope_low_freq_factor=float(scaling["low_freq_factor"]),
                rope_high_freq_factor=float(scaling["high_freq_factor"]))
    # decoupled head_dim (Mistral-Nemo style): qkv projects to
    # (nh + 2*kv) * head_dim independent of hidden_size/num_heads
    hd_cfg = getattr(config, "head_dim", None)
    # bias flags are PRESENCE-driven (the config attr alone is a trap: a
    # fresh Qwen2 carries zero-initialized q/k/v biases that a config-only
    # check could drop while still passing random-init parity)
    qkv_bias = prefix + "layers.0.self_attn.q_proj.bias" in sd
    o_bias = prefix + "layers.0.self_attn.o_proj.bias" in sd
    mlp_bias = prefix + "layers.0.mlp.gate_proj.bias" in sd
    qk_norm = prefix + "layers.0.self_attn.q_norm.weight" in sd
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings,
        hidden_size=config.hidden_size,
        num_layers=L,
        num_heads=config.num_attention_heads,
        num_kv_heads=kv,
        mlp_dim_override=config.intermediate_size,
        norm="rmsnorm",
        gated_mlp=True,
        activation=activation,
        embed_scale=embed_scale,
        pos_embed="rotary",
        rotary_interleaved=False,           # HF rotate_half layout
        rope_theta=float(getattr(config, "rope_theta", 10000.0)),
        head_dim_override=int(hd_cfg) if hd_cfg else None,
        use_bias=False,
        # Llama attention_bias=True: q/k/v/o biased; Qwen2: q/k/v only
        qkv_bias=qkv_bias,
        attn_out_bias=o_bias,
        mlp_bias=mlp_bias,
        qk_norm=qk_norm,
        tie_embeddings=tie,
        layer_norm_eps=float(config.rms_norm_eps),
        layer_windows=windows,
        scan_layers=True,
        # Mixtral: SwiGLU experts behind a top-k router. The capacity
        # factor E/k makes the GShard queues drop-free (worst-case load is
        # one queue slot per token per expert), matching HF's capacity-less
        # routing exactly at eval
        moe_experts=moe_experts,
        moe_k=moe_k,
        moe_capacity_factor=(float(moe_experts) / moe_k if moe_experts
                             else 1.25),
        moe_aux_weight=float(getattr(config, "router_aux_loss_coef", 0.01)),
        # Gemma-2: sandwich norms, tanh softcapping on attention scores and
        # final logits, and the query_pre_attn_scalar attention scale
        post_block_norms=gemma2,
        attn_softcap=(float(getattr(config, "attn_logit_softcapping", 0)
                            or 0) if gemma2 else 0.0),
        final_logit_softcap=(float(getattr(config,
                                           "final_logit_softcapping", 0)
                                   or 0) if gemma2 else 0.0),
        attn_scale=(float(config.query_pre_attn_scalar) ** -0.5
                    if gemma2 else None),
        **rope_kwargs,
    )
    params, g = _llama_family_params(sd, prefix, L, qkv_bias=qkv_bias,
                                     o_bias=o_bias, mlp_bias=mlp_bias,
                                     qk_norm=qk_norm,
                                     moe_experts=moe_experts,
                                     norm_plus_one=norm_plus_one,
                                     sandwich_norms=gemma2)
    if not tie:
        if "lm_head.weight" not in sd:
            # fail loudly like every other CausalLM loader — fabricating a
            # tied head for an untied checkpoint would decode garbage
            raise KeyError(
                "untied checkpoint (tie_word_embeddings=False) has no "
                "lm_head.weight — is this a bare LlamaModel state dict? "
                "Export the ForCausalLM model, or set tie_word_embeddings")
        params["lm_head"] = {"kernel": _np(sd["lm_head.weight"]).T}
    return _to_f32(params), cfg


def load_hf_llama(model_or_state_dict, config=None):
    """Llama/Llama-2/3 (HF LlamaForCausalLM): RMSNorm pre-norm, SwiGLU MLP,
    GQA, rotate_half rotary with config rope_theta. Exceeds the reference's
    replace_policy list (v0.8.1 pre-dates Llama)."""
    return _load_hf_llama_family(model_or_state_dict, config)


def load_hf_mistral(model_or_state_dict, config=None):
    """Mistral (HF MistralForCausalLM): the Llama block family plus a
    uniform sliding attention window on every layer."""
    return _load_hf_llama_family(model_or_state_dict, config,
                                 use_sliding_window=True)


def load_hf_qwen2(model_or_state_dict, config=None):
    """Qwen2/Qwen2.5 (HF Qwen2ForCausalLM): the Llama block family with
    q/k/v biases (no o bias — detected from the state dict), optionally
    tied embeddings, and a sliding window gated behind use_sliding_window
    with the first max_window_layers on full attention."""
    return _load_hf_llama_family(model_or_state_dict, config,
                                 use_sliding_window="qwen2")


def load_hf_qwen3(model_or_state_dict, config=None):
    """Qwen3 (policy 15): the Llama block family with per-head q/k RMSNorm
    before rotary, a decoupled head_dim, no attention biases, and per-layer
    sliding windows driven by config.layer_types."""
    return _load_hf_llama_family(model_or_state_dict, config,
                                 use_sliding_window="layer_types")


def load_hf_falcon(model_or_state_dict, config=None):
    """Falcon (policy 20, HF FalconForCausalLM), two supported variants:

    * 7B-style (multi_query, parallel_attn, single input_layernorm):
      GPT-J-style parallel residual with a shared LN, MQA (kv=1), fused
      query_key_value already in q|k|v order.
    * 40B-style (new_decoder_architecture): parallel residual with SEPARATE
      ln_attn/ln_mlp (our parallel_residual_dual_ln), GQA, and the fused
      qkv interleaved PER KV GROUP ([q_g0.., k0, v0, q_g1.., k1, v1]) —
      de-interleaved here into the q|k|v kernel layout.

    Both: rotate_half rotary, exact-erf GELU MLP, no biases except the
    layernorms, tied embeddings. Legacy falcon-rw variants (alibi or
    sequential blocks) are refused loudly."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "transformer.")
    g = lambda n: _np(sd[prefix + n])
    L = config.num_hidden_layers
    nh = config.num_attention_heads
    H = config.hidden_size
    hd = H // nh
    new_arch = bool(getattr(config, "new_decoder_architecture", False))
    if getattr(config, "alibi", False) or not (
            new_arch or getattr(config, "parallel_attn", False)):
        raise NotImplementedError(
            "only the rotary parallel-attention Falcon variants are "
            "supported (7B-style multi_query/parallel_attn or 40B-style "
            "new_decoder_architecture); alibi / sequential falcon-rw "
            "checkpoints would load with the wrong block math")
    if new_arch:
        kv = int(config.num_kv_heads)
    elif getattr(config, "multi_query", True):
        kv = 1
    else:
        raise NotImplementedError(
            "Falcon multi_query=False (per-head-interleaved MHA qkv) is "
            "not supported")
    if getattr(config, "rope_scaling", None):
        raise NotImplementedError(
            f"Falcon rope_scaling={config.rope_scaling} is not wired into "
            "this policy; loading with plain rope_theta would produce "
            "wrong frequencies")
    if prefix + "h.0.self_attention.query_key_value.bias" in sd:
        raise NotImplementedError(
            "Falcon config.bias=True checkpoints (biased linears) are not "
            "supported; silently dropping the biases would change every "
            "projection")
    # Falcon2-11B: new_decoder_architecture with ONE shared layernorm
    # (num_ln_in_parallel_attn=1) — presence-driven, like the bias flags
    dual_ln = new_arch and prefix + "h.0.ln_attn.weight" in sd

    def qkv(i):
        w = g(f"h.{i}.self_attention.query_key_value.weight")
        if new_arch:
            # [(kv, nh/kv + 2, hd), H] groups -> contiguous q | k | v
            w = w.reshape(kv, nh // kv + 2, hd, H)
            q = w[:, :-2].reshape(nh * hd, H)
            k = w[:, -2].reshape(kv * hd, H)
            v = w[:, -1].reshape(kv * hd, H)
            w = np.concatenate([q, k, v], axis=0)
        return w.T                                  # [H, (nh + 2*kv) * hd]

    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=getattr(config, "max_position_embeddings", 2048),
        hidden_size=H,
        num_layers=L,
        num_heads=nh,
        num_kv_heads=kv,
        mlp_dim_override=int(getattr(config, "ffn_hidden_size", None)
                             or 4 * H),
        # strict map (HF get_activation(config.activation); "gelu" = erf):
        # unknown activations fail at load, not in apply
        activation={"gelu": "gelu_exact", "gelu_pytorch_tanh": "gelu",
                    "gelu_new": "gelu", "relu": "relu"}[
            getattr(config, "activation", "gelu")],
        pos_embed="rotary",
        rotary_interleaved=False,                   # rotate_half
        rope_theta=float(getattr(config, "rope_theta", 10000.0)),
        parallel_residual=True,
        parallel_residual_dual_ln=dual_ln,
        use_bias=False,
        tie_embeddings=True,
        layer_norm_eps=float(config.layer_norm_epsilon),
        scan_layers=True,
    )
    stack = _stacker(g, L)
    ln1 = "ln_attn" if dual_ln else "input_layernorm"
    blocks = {
        "ln1": {"scale": stack(lambda i: g(f"h.{i}.{ln1}.weight")),
                "bias": stack(lambda i: g(f"h.{i}.{ln1}.bias"))},
        "attn_qkv": {"kernel": stack(qkv)},
        "attn_proj": {"kernel": stack(
            lambda i: g(f"h.{i}.self_attention.dense.weight").T)},
        "mlp_fc": {"kernel": stack(
            lambda i: g(f"h.{i}.mlp.dense_h_to_4h.weight").T)},
        "mlp_proj": {"kernel": stack(
            lambda i: g(f"h.{i}.mlp.dense_4h_to_h.weight").T)},
    }
    if dual_ln:
        blocks["ln2"] = {"scale": stack(lambda i: g(f"h.{i}.ln_mlp.weight")),
                         "bias": stack(lambda i: g(f"h.{i}.ln_mlp.bias"))}
    params = {
        "wte": {"embedding": g("word_embeddings.weight")},
        "blocks": blocks,
        "ln_f": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }
    return _to_f32(params), cfg


def load_hf_gpt_bigcode(model_or_state_dict, config=None):
    """GPT-BigCode / StarCoder (policy 19, HF GPTBigCodeForCausalLM): the
    GPT-2 block family with MULTI-QUERY attention — one shared k/v head.
    HF's fused c_attn is [H + 2*head_dim, H] with q first, then the single
    k and v head: exactly our GQA qkv kernel layout at num_kv_heads=1, so
    the kernel maps with only a transpose (nn.Linear, not GPT-2's Conv1D).
    tanh-GELU MLP, learned positions, tied embeddings."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "transformer.")
    g = lambda n: _np(sd[prefix + n])
    L = config.n_layer
    if not getattr(config, "multi_query", True):
        raise NotImplementedError(
            "GPTBigCode with multi_query=False stores c_attn in the "
            "interleaved per-head MHA layout; only the multi-query form "
            "(StarCoder) is supported")
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.n_positions,
        hidden_size=config.n_embd,
        num_layers=L,
        num_heads=config.n_head,
        num_kv_heads=1,                       # MQA
        mlp_dim_override=config.n_inner or 4 * config.n_embd,
        # strict mapping like the NeoX/BERT loaders: unknown activations
        # fail at load, and HF "gelu" (exact erf) is NOT our tanh "gelu"
        activation={"gelu_pytorch_tanh": "gelu", "gelu_new": "gelu",
                    "gelu": "gelu_exact", "relu": "relu"}[
            getattr(config, "activation_function", "gelu_pytorch_tanh")],
        tie_embeddings=True,
        scan_layers=True,
        layer_norm_eps=float(config.layer_norm_epsilon),
    )
    _stk = _stacker(g, L)
    stack = lambda name, t=True: _stk(
        lambda i: g(f"h.{i}.{name}").T if t else g(f"h.{i}.{name}"))
    blocks = {
        "ln1": {"scale": stack("ln_1.weight", t=False),
                "bias": stack("ln_1.bias", t=False)},
        "attn_qkv": {"kernel": stack("attn.c_attn.weight"),
                     "bias": stack("attn.c_attn.bias", t=False)},
        "attn_proj": {"kernel": stack("attn.c_proj.weight"),
                      "bias": stack("attn.c_proj.bias", t=False)},
        "ln2": {"scale": stack("ln_2.weight", t=False),
                "bias": stack("ln_2.bias", t=False)},
        "mlp_fc": {"kernel": stack("mlp.c_fc.weight"),
                   "bias": stack("mlp.c_fc.bias", t=False)},
        "mlp_proj": {"kernel": stack("mlp.c_proj.weight"),
                     "bias": stack("mlp.c_proj.bias", t=False)},
    }
    params = {
        "wte": {"embedding": g("wte.weight")},
        "wpe": {"embedding": g("wpe.weight")},
        "blocks": blocks,
        "ln_f": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }
    return _to_f32(params), cfg


def load_hf_phi(model_or_state_dict, config=None):
    """Phi-1/1.5/2 (policy 18, HF PhiForCausalLM): GPT-J-style parallel
    residual with a SINGLE shared LayerNorm feeding both branches
    (PhiDecoderLayer.forward: attn(ln(x)) + mlp(ln(x)) + x), partial
    rotate_half rotary over partial_rotary_factor * head_dim channels,
    biased q/k/v/dense and fc1/fc2, and a biased untied lm_head."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    prefix = _prefix(sd, "model.")
    L = config.num_hidden_layers
    if getattr(config, "qk_layernorm", False):
        raise NotImplementedError(
            "PhiConfig.qk_layernorm=True (per-head q/k LayerNorm with "
            "biases) is not supported; loading without it would silently "
            "change every attention score")
    g = lambda n: _np(sd[prefix + n])
    stack = _stacker(g, L)
    qkv, qkv_b = _concat_qkv_linear(
        g, "layers.{i}.self_attn.{p}_proj.weight")
    nh = config.num_attention_heads
    kv = getattr(config, "num_key_value_heads", None) or nh
    hd = config.hidden_size // nh
    cfg = TransformerConfig(
        vocab_size=config.vocab_size,
        max_seq_len=config.max_position_embeddings,
        hidden_size=config.hidden_size,
        num_layers=L,
        num_heads=nh,
        num_kv_heads=kv,
        mlp_dim_override=config.intermediate_size,
        activation="gelu",                  # HF gelu_new = tanh approx
        pos_embed="rotary",
        rotary_dim=int(config.partial_rotary_factor * hd),
        rotary_interleaved=False,           # rotate_half
        rope_theta=float(getattr(config, "rope_theta", 10000.0)),
        parallel_residual=True,             # shared ln1 feeds both branches
        use_bias=True,
        tie_embeddings=False,
        lm_head_bias=True,
        layer_norm_eps=float(config.layer_norm_eps),
        scan_layers=True,
    )
    blocks = {
        "ln1": {"scale": stack(
            lambda i: g(f"layers.{i}.input_layernorm.weight")),
            "bias": stack(
            lambda i: g(f"layers.{i}.input_layernorm.bias"))},
        "attn_qkv": {"kernel": stack(qkv), "bias": stack(qkv_b)},
        "attn_proj": {"kernel": stack(
            lambda i: g(f"layers.{i}.self_attn.dense.weight").T),
            "bias": stack(lambda i: g(f"layers.{i}.self_attn.dense.bias"))},
        "mlp_fc": {"kernel": stack(
            lambda i: g(f"layers.{i}.mlp.fc1.weight").T),
            "bias": stack(lambda i: g(f"layers.{i}.mlp.fc1.bias"))},
        "mlp_proj": {"kernel": stack(
            lambda i: g(f"layers.{i}.mlp.fc2.weight").T),
            "bias": stack(lambda i: g(f"layers.{i}.mlp.fc2.bias"))},
    }
    params = {
        "wte": {"embedding": g("embed_tokens.weight")},
        "blocks": blocks,
        "ln_f": {"scale": g("final_layernorm.weight"),
                 "bias": g("final_layernorm.bias")},
        "lm_head": {"kernel": _np(sd["lm_head.weight"]).T,
                    "bias": _np(sd["lm_head.bias"])},
    }
    return _to_f32(params), cfg


def load_hf_gemma(model_or_state_dict, config=None):
    """Gemma (policy 17): the Llama block family with three deltas —
    RMSNorm weights stored as w with forward x*(1+w) (folded into the
    scale at load), token embeddings scaled by sqrt(hidden_size) in the
    compute dtype, and a tanh-GELU gated MLP. head_dim is decoupled
    (256 at 7B) and embeddings are always tied."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    return _load_hf_llama_family(
        sd, config, activation="gelu",
        embed_scale=float(config.hidden_size) ** 0.5,
        norm_plus_one=True)


def load_hf_gemma2(model_or_state_dict, config=None):
    """Gemma-2 (policy 21): Gemma's deltas plus sandwich norms (each branch
    output normed again before its residual), tanh softcapping on attention
    scores (routes attention to the exact reference impl) and final logits,
    query_pre_attn_scalar attention scaling, and alternating
    sliding/full-attention layers via config.layer_types."""
    sd, config = _sd_and_config(model_or_state_dict, config)
    return _load_hf_llama_family(
        sd, config, use_sliding_window="layer_types", activation="gelu",
        embed_scale=float(config.hidden_size) ** 0.5,
        norm_plus_one=True, gemma2=True)


def load_hf_mixtral(model_or_state_dict, config=None):
    """Mixtral (policy 16): the Mistral block family with the dense SwiGLU
    MLP replaced by num_local_experts SwiGLU experts behind a
    top-(num_experts_per_tok) router (HF block_sparse_moe gate + w1/w3/w2
    experts -> moe/layer.MoE with GatedExpertMLP)."""
    return _load_hf_llama_family(model_or_state_dict, config,
                                 use_sliding_window=True, moe=True)


HF_POLICIES = {
    "llama": load_hf_llama,
    "LlamaForCausalLM": load_hf_llama,
    "mistral": load_hf_mistral,
    "MistralForCausalLM": load_hf_mistral,
    "qwen2": load_hf_qwen2,
    "Qwen2ForCausalLM": load_hf_qwen2,
    "qwen3": load_hf_qwen3,
    "Qwen3ForCausalLM": load_hf_qwen3,
    "mixtral": load_hf_mixtral,
    "MixtralForCausalLM": load_hf_mixtral,
    "gemma": load_hf_gemma,
    "GemmaForCausalLM": load_hf_gemma,
    "gemma2": load_hf_gemma2,
    "Gemma2ForCausalLM": load_hf_gemma2,
    "phi": load_hf_phi,
    "PhiForCausalLM": load_hf_phi,
    "gpt_bigcode": load_hf_gpt_bigcode,
    "GPTBigCodeForCausalLM": load_hf_gpt_bigcode,
    "falcon": load_hf_falcon,
    "FalconForCausalLM": load_hf_falcon,
    "gptneo": load_hf_gpt_neo,
    "GPTNeoForCausalLM": load_hf_gpt_neo,
    "gptj": load_hf_gptj,
    "GPTJForCausalLM": load_hf_gptj,
    "gpt2": load_hf_gpt2,
    "GPT2LMHeadModel": load_hf_gpt2,
    "opt": load_hf_opt,
    "OPTForCausalLM": load_hf_opt,
    "bloom": load_hf_bloom,
    "BloomForCausalLM": load_hf_bloom,
    "bert": load_hf_bert,
    "BertForMaskedLM": load_hf_bert,
    "roberta": load_hf_roberta,
    "RobertaForMaskedLM": load_hf_roberta,
    "distilbert": load_hf_distilbert,
    "DistilBertForMaskedLM": load_hf_distilbert,
    "gptneox": load_hf_gpt_neox,
    "GPTNeoXForCausalLM": load_hf_gpt_neox,
    "clip": load_hf_clip_text,
    "CLIPTextModel": load_hf_clip_text,
    # CLIPTextModelWithProjection is deliberately NOT aliased: its output is
    # text_embeds through text_projection, which this encoder-only policy
    # does not model — aliasing it would silently return the wrong tensor
}


def load_hf(model, arch: str = None, config=None):
    """Dispatch on HF architecture name (reference: replace_module.py policy
    matching by class). Exact matches only: substring matching misfires on
    sibling arches (GPTNeoX contains 'gptneo', Roberta contains 'bert').
    ``config``: explicit HF config for the raw-state-dict path (live models
    carry their own)."""
    arch = arch or type(model).__name__
    fn = HF_POLICIES.get(arch) or HF_POLICIES.get(arch.lower())
    if fn is not None:
        return fn(model, config=config)
    raise NotImplementedError(
        f"no import policy for architecture '{arch}'; have "
        f"{sorted(k for k in HF_POLICIES if not k.islower())}")


def replace_transformer_layer(model, config=None, arch: str = None,
                              dtype=None):
    """Reference-API shim (module_inject/replace_module.py:300): where the
    reference rewires a torch model's layers IN PLACE to fused CUDA
    modules, the TPU-native substitution is functional — the matched
    policy maps the HF weights onto the in-house Transformer (XLA fusion +
    Pallas attention; models/transformer.py) and returns
    ``(module, params, cfg)``. The input torch model is never mutated;
    serve the returned module through InferenceEngine (which calls this
    path itself via ``models.hf.load_hf``).
    """
    import dataclasses
    from .transformer import Transformer
    params, cfg = load_hf(model, arch=arch, config=config)
    if dtype is not None:
        cfg = dataclasses.replace(cfg, dtype=dtype)
    return Transformer(cfg), params, cfg


def revert_transformer_layer(model, *_, **__):
    """Reference-API shim (deepspeed/__init__.py:35): the reference undoes
    its in-place layer surgery. The TPU substitution is functional — the
    original model was never touched — so revert returns it unchanged."""
    return model
