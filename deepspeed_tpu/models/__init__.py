"""In-tree model family (flagship GPT decoder / BERT encoder + presets)."""

from .transformer import (
    Transformer,
    TransformerConfig,
    Block,
    build_model,
    get_config,
    causal_lm_loss,
    masked_lm_loss,
    make_moe_loss,
    cross_entropy,
    fused_loss_passthrough,
)

__all__ = [
    "Transformer", "TransformerConfig", "Block", "build_model", "get_config",
    "causal_lm_loss", "masked_lm_loss", "make_moe_loss", "cross_entropy",
    "fused_loss_passthrough",
]
