"""KV-cache decode + autoregressive generation for the flagship transformer.

Capability slot of the reference's inference decode path: the fused
`softmax_context` attention-with-cache kernels and preallocated KV workspace
(csrc/transformer/inference/, inference_context.h) and `InferenceEngine.
generate` (inference/engine.py:537). TPU-native shape: the cache is a
scan-carried pytree of static-shape buffers ([L, B, heads, max_len, head_dim]),
the decode step is one jitted function (XLA's compilation cache plays the role
of CUDA-graph capture/replay), and sampling runs inside `lax.scan` so the
whole generation loop is a single compiled program.

All functions are pure: (params, cache, ids) -> (logits, cache). They mirror
models/transformer.Block numerically (same params pytree, scan-layers layout).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import TransformerConfig

PyTree = Any


def _layer_norm(x, p, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _dense(x, p):
    y = x @ p["kernel"].astype(x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


def init_cache(cfg: TransformerConfig, batch_size: int, max_len: int,
               dtype=None) -> Dict[str, jnp.ndarray]:
    """Preallocated KV workspace (reference: allocate_workspace, pt_binding)."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, batch_size, cfg.num_heads, max_len, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "pos": jnp.zeros((), jnp.int32)}


def ensure_scan_layout(params: PyTree, num_layers: int) -> PyTree:
    """Restack a scan_layers=False param tree (blocks_0..blocks_{L-1}) into the
    scanned layout (blocks leaves [L, ...]) that the decode path consumes."""
    if "blocks" in params:
        return params
    names = [f"blocks_{i}" for i in range(num_layers)]
    missing = [n for n in names if n not in params]
    if missing:
        raise ValueError(
            f"params have neither 'blocks' (scan layout) nor all of "
            f"blocks_0..blocks_{num_layers - 1} (missing {missing[:3]}...); "
            "cannot build the KV-cache decode layout")
    stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves),
                           *[params[n] for n in names])
    rest = {k: v for k, v in params.items() if k not in names}
    return {**rest, "blocks": stacked}


def forward_with_cache(cfg: TransformerConfig, params: PyTree,
                       input_ids: jnp.ndarray, cache: Dict
                       ) -> Tuple[jnp.ndarray, Dict]:
    """Run T_new tokens at positions [cache.pos, cache.pos+T_new) against the
    cache. Returns (logits [B, T_new, V], updated cache). Params must be the
    scan-layers layout (blocks leaves [L, ...]) — use ensure_scan_layout to
    restack a per-layer tree."""
    if cfg.moe_experts > 0:
        raise NotImplementedError("KV-cache decode for MoE models lands later")
    if "blocks" not in params:
        raise ValueError(
            "forward_with_cache needs scan-layers params (a 'blocks' subtree "
            "stacked [L, ...]); this model was built with scan_layers=False — "
            "restack with models.generation.ensure_scan_layout(params, L)")
    B, T_new = input_ids.shape
    pos = cache["pos"]
    max_len = cache["k"].shape[3]
    nh, hd = cfg.num_heads, cfg.head_dim

    wte = params["wte"]["embedding"]
    wpe = params["wpe"]["embedding"]
    x = (wte.astype(cfg.dtype)[input_ids] +
         wpe.astype(cfg.dtype)[pos + jnp.arange(T_new)][None])

    q_abs = pos + jnp.arange(T_new)                 # [T_new]
    k_pos = jnp.arange(max_len)                     # [max_len]
    # causal-with-cache mask [T_new, max_len]
    mask = k_pos[None, :] <= q_abs[:, None]

    def layer(x, xs):
        p, k_cache, v_cache = xs                    # k/v: [B, nh, max_len, hd]
        h = _layer_norm(x, p["ln1"], cfg.layer_norm_eps)
        qkv = _dense(h, p["attn_qkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(B, T_new, nh, hd).transpose(0, 2, 1, 3)
        q, k, v = to_heads(q), to_heads(k), to_heads(v)
        k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, 0, pos, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, 0, pos, 0))
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cache).astype(jnp.float32)
        s = s / np.sqrt(hd)
        s = jnp.where(mask[None, None], s, -1e30)
        prob = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bhkd->bhqd", prob, v_cache)
        o = o.transpose(0, 2, 1, 3).reshape(B, T_new, nh * hd)
        x = x + _dense(o, p["attn_proj"])
        h = _layer_norm(x, p["ln2"], cfg.layer_norm_eps)
        h = _dense(h, p["mlp_fc"])
        h = jax.nn.gelu(h)
        x = x + _dense(h, p["mlp_proj"])
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (params["blocks"], cache["k"], cache["v"]))
    x = _layer_norm(x, params["ln_f"], cfg.layer_norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bth,vh->btv", x, wte.astype(x.dtype))
    else:
        logits = _dense(x, params["lm_head"])
    new_cache = {"k": k_new, "v": v_new, "pos": pos + T_new}
    return logits.astype(jnp.float32), new_cache


def _sample(logits, rng, temperature: float, top_k: Optional[int]):
    """logits [B, V] -> token ids [B]."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1)


@partial(jax.jit, static_argnums=(0, 3, 4, 6))
def generate(cfg: TransformerConfig,
             params: PyTree,
             input_ids: jnp.ndarray,
             max_new_tokens: int,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None,
             top_k: Optional[int] = None) -> jnp.ndarray:
    """Prefill + single-token decode loop, one compiled program.

    input_ids [B, T_prompt] -> [B, T_prompt + max_new_tokens].
    """
    B, T_in = input_ids.shape
    max_len = T_in + max_new_tokens
    if max_len > cfg.max_seq_len:
        raise ValueError(f"generation length {max_len} exceeds max_seq_len "
                         f"{cfg.max_seq_len}")
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    params = ensure_scan_layout(params, cfg.num_layers)
    cache = init_cache(cfg, B, max_len)
    logits, cache = forward_with_cache(cfg, params, input_ids, cache)
    rng, r0 = jax.random.split(rng)
    tok = _sample(logits[:, -1], r0, temperature, top_k)

    def step(carry, _):
        tok, cache, rng = carry
        logits, cache = forward_with_cache(cfg, params, tok[:, None], cache)
        rng, r = jax.random.split(rng)
        nxt = _sample(logits[:, -1], r, temperature, top_k)
        return (nxt, cache, rng), tok

    (last, _, _), toks = jax.lax.scan(
        step, (tok, cache, rng), None, length=max_new_tokens - 1)
    out = jnp.concatenate([toks.T, last[:, None]], axis=1)  # [B, max_new]
    return jnp.concatenate([input_ids, out], axis=1)
