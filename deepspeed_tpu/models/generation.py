"""KV-cache decode + autoregressive generation for the flagship transformer.

Capability slot of the reference's inference decode path: the fused
`softmax_context` attention-with-cache kernels and preallocated KV workspace
(csrc/transformer/inference/, inference_context.h) and `InferenceEngine.
generate` (inference/engine.py:537). TPU-native shape: the cache is a
scan-carried pytree of static-shape buffers ([L, B, heads, max_len, head_dim]),
the decode step is one jitted function (XLA's compilation cache plays the role
of CUDA-graph capture/replay), and sampling runs inside `lax.scan` so the
whole generation loop is a single compiled program.

All functions are pure: (params, cache, ids) -> (logits, cache). They mirror
models/transformer.Block numerically (same params pytree, scan-layers layout).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..quant_format import kv_quantize as _kv_quantize  # noqa: F401 (shared
#   format, round 17 — re-exported: serving/model_runner imports it here)
from .transformer import TransformerConfig

PyTree = Any


def _layer_norm(x, p, eps, rms: bool = False):
    xf = x.astype(jnp.float32)
    if rms:
        # RMSNorm (Llama family): uncentered, scale-only
        y = xf * jax.lax.rsqrt(
            jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
        return (y * p["scale"]).astype(x.dtype)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _kernel_of(p, dtype):
    """Matmul weight, dequantizing the int8 weight-only forms in place.

    int8 kernels carry either a per-output-channel symmetric scale
    (``kernel_scale``, the inference engine's format) or per-256-element
    blockwise scales along the contraction dim (``kernel_qscale``, the
    round-17 serving pack — quant_format's wire format on a weight); the
    convert+multiply fuses into the consuming dot, so the HBM read is
    half the bf16 bytes — the role of the reference's int8 inference
    kernels (csrc/transformer/inference, pt_binding ds_*_int8 entry
    points). The serving decode hot path does NOT come through here for
    blockwise kernels: ``_dense`` routes those to the Pallas
    ``quant_matmul`` kernel, which dequantizes per block IN-kernel —
    this full materialization is the einsum/oracle fallback only."""
    k = p["kernel"]
    if "kernel_qscale" in p:
        # blockwise along the contraction dim: q [..., Kp, N] int8,
        # scales [..., Kp/block, N] f32 -> w[i, n] = q[i, n] * s[i//block, n]
        # (Kp is the padded contraction — padded rows dequantize to 0)
        s = p["kernel_qscale"]
        nkb = s.shape[-2]
        qb = k.shape[-2] // nkb
        w = (k.astype(jnp.float32).reshape(
                k.shape[:-2] + (nkb, qb, k.shape[-1]))
             * s[..., :, None, :])
        return w.reshape(k.shape).astype(dtype)
    if "kernel_scale" in p:
        # dequantize in f32: the scale is deliberately stored f32 by the
        # inference engine, and an int8->f32 multiply keeps the scale/2
        # error bound; casting the scale to bf16 first would add ~0.4%
        # rounding on top of the quantization error
        return (k.astype(jnp.float32) * p["kernel_scale"]).astype(dtype)
    return k.astype(dtype)


def _dense(x, p, interpret: bool = False):
    if "kernel_qscale" in p:
        # round 17: blockwise-int8 packed kernel (serving.weight_dtype
        # "int8") — int8 stays int8 until the Pallas kernel's VMEM
        # dequant; no full-weight f32/bf16 copy materializes here
        from ..ops.pallas.quant_matmul import quant_matmul
        y = quant_matmul(x, p["kernel"], p["kernel_qscale"],
                         interpret=interpret)
    else:
        y = x @ _kernel_of(p, x.dtype)
    if "bias" in p:
        y = y + p["bias"].astype(x.dtype)
    return y


# cache lengths round up to this so the decode kernel always has a >=128
# block tiling (ops/pallas/decode_attention.py); dead positions are masked
KV_CACHE_ROUND = 256


def padded_cache_len(n: int) -> int:
    return -(-n // KV_CACHE_ROUND) * KV_CACHE_ROUND


def init_cache(cfg: TransformerConfig, batch_size: int, max_len: int,
               dtype=None, pad_lens=None) -> Dict[str, jnp.ndarray]:
    """Preallocated KV workspace (reference: allocate_workspace, pt_binding).

    ``pad_lens`` [B]: per-sample LEFT-pad lengths for ragged batched
    prompts — cache slots [0, pad_i) are dead for sample i (masked in every
    attention) and logical positions are slot - pad_i. Absent for uniform
    batches (the decode kernel path needs the uniform layout).

    ``dtype=jnp.int8``: quantized KV cache — k/v store int8 with a
    per-(layer, batch, head, position) f32 scale (symmetric over the head
    dim), halving the cache's HBM footprint vs bf16 (+~3% for scales):
    2x the context length or batch fits the same workspace. Attention
    dequantizes on read (jnp path; the block-skip decode kernel needs the
    bf16 layout and is bypassed). Capability slot of the reference's int8
    inference kernel family (csrc/transformer/inference ds_*_int8)."""
    dtype = dtype or cfg.dtype
    shape = (cfg.num_layers, batch_size, cfg.num_heads, max_len, cfg.head_dim)
    if dtype == jnp.int8:
        cache = {"k": jnp.zeros(shape, jnp.int8),
                 "v": jnp.zeros(shape, jnp.int8),
                 "k_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
                 "v_scale": jnp.zeros(shape[:-1] + (1,), jnp.float32),
                 "pos": jnp.zeros((), jnp.int32)}
    else:
        cache = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
                 "pos": jnp.zeros((), jnp.int32)}
    if pad_lens is not None:
        cache["pad"] = jnp.asarray(pad_lens, jnp.int32)
    return cache


def ensure_scan_layout(params: PyTree, num_layers: int) -> PyTree:
    """Restack a scan_layers=False param tree (blocks_0..blocks_{L-1}) into the
    scanned layout (blocks leaves [L, ...]) that the decode path consumes."""
    if "blocks" in params:
        return params
    names = [f"blocks_{i}" for i in range(num_layers)]
    missing = [n for n in names if n not in params]
    if missing:
        raise ValueError(
            f"params have neither 'blocks' (scan layout) nor all of "
            f"blocks_0..blocks_{num_layers - 1} (missing {missing[:3]}...); "
            "cannot build the KV-cache decode layout")
    stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves),
                           *[params[n] for n in names])
    rest = {k: v for k, v in params.items() if k not in names}
    return {**rest, "blocks": stacked}


def _moe_mlp(cfg: TransformerConfig, p_moe, h):
    """Decode-path MoE MLP: same gating math as moe/layer.MoE with a no-drop
    capacity — incremental decode can't see the other timesteps a capacity
    limit would make it compete with (run eval with a capacity_factor that
    avoids drops for exact decode/full-forward parity)."""
    from ..moe.sharded_moe import top1_gating, top2_gating
    B, T, H = h.shape
    tokens = h.reshape(B * T, H)
    gate_logits = tokens.astype(jnp.float32) @ p_moe["gate"]["kernel"]
    gating = top1_gating if cfg.moe_k == 1 else top2_gating
    _aux, combine, dispatch, _ = gating(gate_logits, capacity=B * T)
    disp = jnp.einsum("tec,th->ech", dispatch.astype(h.dtype), tokens)

    def edense(x, p, contract="ech,ehm->ecm"):
        y = jnp.einsum(contract, x, _kernel_of(p, h.dtype))
        if "bias" in p:
            y = y + p["bias"][:, None].astype(h.dtype)
        return y

    if "gate" in p_moe["experts"]:
        # SwiGLU experts (Mixtral family): proj(act(gate(x)) * fc(x))
        from .transformer import _ACTIVATIONS
        act = _ACTIVATIONS[cfg.activation]
        g = act(edense(disp, p_moe["experts"]["gate"]))
        hh = g * edense(disp, p_moe["experts"]["fc"])
    else:
        hh = jax.nn.gelu(edense(disp, p_moe["experts"]["fc"]))
    out = edense(hh, p_moe["experts"]["proj"], "ecm,emh->ech")
    y = jnp.einsum("tec,ech->th", combine.astype(h.dtype), out)
    return y.reshape(B, T, H)


def forward_with_cache(cfg: TransformerConfig, params: PyTree,
                       input_ids: jnp.ndarray, cache: Dict,
                       prefer_kernel: Optional[bool] = None,
                       prefill_flash=False
                       ) -> Tuple[jnp.ndarray, Dict]:
    """Run T_new tokens at positions [cache.pos, cache.pos+T_new) against the
    cache. Returns (logits [B, T_new, V], updated cache). Params must be the
    scan-layers layout (blocks leaves [L, ...]) — use ensure_scan_layout to
    restack a per-layer tree.

    ``prefill_flash``: the caller guarantees the cache is EMPTY (pos == 0) —
    the prefill attention then runs the Pallas flash kernel over the fresh
    K/V (causal, with in-kernel alibi slopes / softcap / uniform sliding
    window) instead of masking the whole preallocated cache, so prefill cost
    scales with the prompt, not max_len. TPU only (pass "interpret" to force
    the interpreted kernel in tests); ragged (left-padded), int8-cache, and
    mixed-per-layer-window models keep the jnp path.

    Covers the policy architectures: rotary/alibi positions, parallel
    residual (GPT-J), per-layer local windows (GPT-Neo), relu/gelu
    activations, unscaled attention, MoE MLPs. post_ln (BERT) has no decode
    path — encoders don't generate."""
    if cfg.post_ln:
        raise NotImplementedError("post-LN encoders (BERT) do not decode")
    if "blocks" not in params:
        raise ValueError(
            "forward_with_cache needs scan-layers params (a 'blocks' subtree "
            "stacked [L, ...]); this model was built with scan_layers=False — "
            "restack with models.generation.ensure_scan_layout(params, L)")
    B, T_new = input_ids.shape
    pos = cache["pos"]
    max_len = cache["k"].shape[3]
    nh, hd = cfg.num_heads, cfg.head_dim
    kvh = cfg.kv_heads
    rms = cfg.norm == "rmsnorm"
    from .transformer import _ACTIVATIONS, alibi_slopes, apply_rotary
    act = _ACTIVATIONS[cfg.activation]
    sm_scale = (cfg.attn_scale if cfg.attn_scale is not None
                else 1.0 / np.sqrt(hd))

    wte = params["wte"]["embedding"]
    x = wte.astype(cfg.dtype)[input_ids]
    if cfg.embed_scale is not None:
        x = x * jnp.asarray(cfg.embed_scale, x.dtype)
    q_abs = pos + jnp.arange(T_new)                 # cache-slot positions [T]
    pad = cache.get("pad")                          # [B] left-pad lengths
    # logical positions (rotary / learned-wpe / HF position_ids semantics):
    # slot - pad for left-padded ragged batches, the slot itself otherwise
    if pad is not None:
        q_log = jnp.maximum(q_abs[None, :] - pad[:, None], 0)    # [B, T]
    else:
        q_log = q_abs
    if cfg.pos_embed == "learned":
        wpe = params["wpe"]["embedding"].astype(cfg.dtype)
        x = x + (wpe[q_log] if pad is not None else wpe[q_log][None])
    if cfg.embed_ln:
        x = _layer_norm(x, params["ln_emb"], cfg.layer_norm_eps, rms)

    k_pos = jnp.arange(max_len)                     # [max_len]
    # causal-with-cache mask [T_new, max_len]
    mask = k_pos[None, :] <= q_abs[:, None]
    if pad is not None:
        # dead left-pad slots never attend (per sample): [B, T, max_len]
        mask = mask[None] & (k_pos[None, None, :] >= pad[:, None, None])
    ali = None
    if cfg.pos_embed == "alibi":
        slopes = jnp.asarray(alibi_slopes(nh), jnp.float32)
        dist = (k_pos[None, :] - q_abs[:, None]).astype(jnp.float32)
        ali = slopes[:, None, None] * dist[None]    # [nh, T_new, max_len]

    windows = (jnp.asarray(cfg.layer_windows, jnp.int32)
               if cfg.layer_windows is not None
               else jnp.zeros((cfg.num_layers,), jnp.int32))

    quant_kv = cache["k"].dtype == jnp.int8

    # Pallas decode kernel: visits only the live ceil(cur_len/block_k) K/V
    # blocks — the slot of the reference's fused softmax_context kernels
    # (pt_binding.cpp:1703-1779). Regime-aware routing under "auto"
    # (round-4 measurements, docs/BENCHMARKS.md): the block-skip pays in
    # BATCHED LONG GENERATION (B>=2, a mostly-dead preallocated cache —
    # 1.77x at B=4, 128-prompt + 2048-new, gpt2-350m) and LOSES 2-8x at
    # B=1 / short caches, where per-layer kernel dispatch dominates.
    # ``prefer_kernel`` (generate passes it from the static prompt/gen
    # plan) overrides the local B/max_len heuristic. "flash" forces the
    # kernel. ALiBi slopes and the Gemma-2 softcap run IN-KERNEL (round-8
    # parity with the flash prefill kernel); ragged (left-padded) batches
    # need per-sample masks -> jnp path; the int8 cache needs the dequant
    # read -> jnp path.
    if prefer_kernel is None:
        prefer_kernel = B >= 2 and max_len >= 4 * 512
    use_kernel = ((cfg.attention_impl == "flash"
                   or (cfg.attention_impl == "auto" and prefer_kernel))
                  and jax.default_backend() == "tpu"
                  and pad is None and not quant_kv)

    # prefill on the flash kernel (empty cache — caller's contract): alibi,
    # softcap and a UNIFORM static window all run in-kernel; mixed per-layer
    # windows trace through one scan body, so they stay on the jnp path
    uw = cfg.uniform_window()
    uniform_ok = uw is not None
    uniform_window = uw or 0
    flash_interp = prefill_flash == "interpret"
    use_prefill_flash = (bool(prefill_flash) and T_new > 1 and pad is None
                         and not quant_kv and uniform_ok
                         and cfg.attention_impl in ("auto", "flash")
                         and (jax.default_backend() == "tpu" or flash_interp))
    prefill_slopes = (jnp.asarray(alibi_slopes(nh), jnp.float32)
                      if cfg.pos_embed == "alibi" else None)

    def layer(carry, xs):
        # the FULL [L, ...] caches ride in the carry so the per-token write
        # is an in-place dynamic-update-slice inside the compiled loop — the
        # stacked-ys layout copied the whole cache every layer (O(L x
        # max_len) HBM traffic per token, the decode bottleneck)
        if quant_kv:
            x, k_all, v_all, ks_all, vs_all = carry
        else:
            x, k_all, v_all = carry
            ks_all = vs_all = None
        p, window, li = xs
        h = _layer_norm(x, p["ln1"], cfg.layer_norm_eps, rms)
        qkv = _dense(h, p["attn_qkv"])
        q, k, v = jnp.split(qkv, [nh * hd, (nh + kvh) * hd], axis=-1)
        to_heads = lambda t, n: t.reshape(B, T_new, n, hd).transpose(
            0, 2, 1, 3)
        q, k, v = to_heads(q, nh), to_heads(k, kvh), to_heads(v, kvh)
        if cfg.qk_norm:
            # Qwen3: per-head RMSNorm on q/k before rotary
            q = _layer_norm(q, p["q_norm"], cfg.layer_norm_eps, rms=True)
            k = _layer_norm(k, p["k_norm"], cfg.layer_norm_eps, rms=True)
        if cfg.pos_embed == "rotary":
            # q_log: logical (pad-corrected) positions — [B, T] for ragged
            # left-padded batches, [T] otherwise (apply_rotary handles both)
            # table covers the cache capacity (dynamic NTK stretches once;
            # None = plain-theta table)
            inv_freq = cfg.rope_inv_freq(max_len)
            q = apply_rotary(q, q_log, cfg.rotary_dim, cfg.rotary_interleaved,
                             cfg.rope_theta, inv_freq=inv_freq)
            k = apply_rotary(k, q_log, cfg.rotary_dim, cfg.rotary_interleaved,
                             cfg.rope_theta, inv_freq=inv_freq)
        if kvh != nh:
            # GQA: repeat kv to full heads BEFORE the cache write — the
            # cache stays [L, B, nh, len, hd], so the decode kernel and
            # int8 tiers apply unchanged. (Storing kv heads only would
            # shrink the cache nh/kvh-fold; future optimization.)
            k = jnp.repeat(k, nh // kvh, axis=1)
            v = jnp.repeat(v, nh // kvh, axis=1)
        if quant_kv:
            k, k_s = _kv_quantize(k)
            v, v_s = _kv_quantize(v)
            ks_all = jax.lax.dynamic_update_slice(ks_all, k_s[None],
                                                  (li, 0, 0, pos, 0))
            vs_all = jax.lax.dynamic_update_slice(vs_all, v_s[None],
                                                  (li, 0, 0, pos, 0))
        k_all = jax.lax.dynamic_update_slice(k_all, k[None],
                                             (li, 0, 0, pos, 0))
        v_all = jax.lax.dynamic_update_slice(v_all, v[None],
                                             (li, 0, 0, pos, 0))
        o = None
        if use_prefill_flash:
            from ..ops.pallas.flash_attention import flash_attention
            # empty cache: attention over the FRESH k/v is exactly the
            # causal prefill; alibi distances from arange positions match
            # q_abs because pos == 0
            o = flash_attention(q, k, v, causal=True, sm_scale=sm_scale,
                                window=uniform_window,
                                softcap=cfg.attn_softcap,
                                alibi_slopes=prefill_slopes,
                                interpret=flash_interp)
        if o is None and use_kernel:
            from ..ops.pallas.decode_attention import decode_attention
            try:
                # stacked form: the kernel indexes layer li out of the
                # carried [L, ...] cache itself — no materialized slice;
                # alibi slopes / softcap ride in-kernel
                o = decode_attention(q, k_all, v_all, pos + T_new,
                                     window=window, sm_scale=sm_scale,
                                     layer_idx=li,
                                     alibi_slopes=prefill_slopes,
                                     softcap=cfg.attn_softcap)
            except ValueError:
                o = None                       # shapes don't tile
        if o is None:
            # the slice reads fuse into the attention consumers (no copy)
            k_cache = jax.lax.dynamic_index_in_dim(k_all, li, 0,
                                                   keepdims=False)
            v_cache = jax.lax.dynamic_index_in_dim(v_all, li, 0,
                                                   keepdims=False)
            if quant_kv:
                # dequantize on read: int8 x f32 per-position scale (the
                # HBM read is the int8 bytes; the multiply fuses)
                k_sc = jax.lax.dynamic_index_in_dim(ks_all, li, 0,
                                                    keepdims=False)
                v_sc = jax.lax.dynamic_index_in_dim(vs_all, li, 0,
                                                    keepdims=False)
                k_cache = (k_cache.astype(jnp.float32) * k_sc).astype(q.dtype)
                v_cache = (v_cache.astype(jnp.float32) * v_sc).astype(q.dtype)
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cache).astype(jnp.float32)
            s = s * sm_scale
            if cfg.attn_softcap:
                from ..ops.attention import apply_softcap
                s = apply_softcap(s, cfg.attn_softcap)
            if ali is not None:
                s = s + ali[None]
            m = mask
            # local sliding window (0 = global); slot distance == logical
            # distance for valid pairs (the left-pad offset cancels)
            win = (q_abs[:, None] - k_pos[None, :] < window) | (window <= 0)
            m = m & (win[None] if m.ndim == 3 else win)
            # mask is [B, T, max_len] for ragged batches, [T, max_len] else
            s = jnp.where(m[:, None] if m.ndim == 3 else m[None, None],
                          s, -1e30)
            prob = jax.nn.softmax(s, axis=-1).astype(x.dtype)
            o = jnp.einsum("bhqk,bhkd->bhqd", prob, v_cache)
        o = o.transpose(0, 2, 1, 3).reshape(B, T_new, nh * hd)
        attn_out = _dense(o, p["attn_proj"])
        if cfg.post_block_norms:
            # Gemma-2 sandwich: norm each branch output pre-residual
            attn_out = _layer_norm(attn_out, p["post_attn_norm"],
                                   cfg.layer_norm_eps, rms)

        def mlp(hin):
            if cfg.moe_experts > 0:
                return _moe_mlp(cfg, p["moe"], hin)
            if cfg.gated_mlp:            # SwiGLU (Llama family)
                g = act(_dense(hin, p["mlp_gate"]))
                return _dense(g * _dense(hin, p["mlp_fc"]), p["mlp_proj"])
            return _dense(act(_dense(hin, p["mlp_fc"])), p["mlp_proj"])

        if cfg.parallel_residual:
            # GPT-NeoX feeds the MLP branch from its own ln2; GPT-J shares ln1
            m_in = (_layer_norm(x, p["ln2"], cfg.layer_norm_eps, rms)
                    if cfg.parallel_residual_dual_ln else h)
            x_out = x + attn_out + mlp(m_in)
        else:
            x_mid = x + attn_out
            h2 = _layer_norm(x_mid, p["ln2"], cfg.layer_norm_eps, rms)
            m = mlp(h2)
            if cfg.post_block_norms:
                m = _layer_norm(m, p["post_mlp_norm"],
                                cfg.layer_norm_eps, rms)
            x_out = x_mid + m
        if quant_kv:
            return (x_out, k_all, v_all, ks_all, vs_all), None
        return (x_out, k_all, v_all), None

    xs = (params["blocks"], windows, jnp.arange(cfg.num_layers))
    if quant_kv:
        (x, k_new, v_new, ks_new, vs_new), _ = jax.lax.scan(
            layer, (x, cache["k"], cache["v"], cache["k_scale"],
                    cache["v_scale"]), xs)
    else:
        (x, k_new, v_new), _ = jax.lax.scan(
            layer, (x, cache["k"], cache["v"]), xs)
    x = _layer_norm(x, params["ln_f"], cfg.layer_norm_eps, rms)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bth,vh->btv", x, wte.astype(x.dtype))
    else:
        logits = _dense(x, params["lm_head"])
    if cfg.final_logit_softcap:
        # stay f32: the return below casts to f32 anyway, and a bf16
        # round-trip of the capped logits could flip near-tie argmaxes
        from ..ops.attention import apply_softcap
        logits = apply_softcap(logits, cfg.final_logit_softcap)
    new_cache = {"k": k_new, "v": v_new, "pos": pos + T_new}
    if quant_kv:
        new_cache["k_scale"] = ks_new
        new_cache["v_scale"] = vs_new
    if pad is not None:
        new_cache["pad"] = pad
    return logits.astype(jnp.float32), new_cache


def apply_top_p(logits, top_p: float):
    """Nucleus filter: keep the smallest prefix of the descending-prob
    distribution with cumulative mass >= top_p, mask the rest (HF
    TopPLogitsWarper semantics: tokens whose cumulative probability AFTER
    themselves exceeds top_p survive; the top token always survives).

    Masking is POSITIONAL in the sorted order (scattered back through the
    inverse permutation), not value-thresholded — tied logits at the
    nucleus boundary keep exactly the sorted-prefix count, as HF does."""
    order = jnp.argsort(-logits, axis=-1)                  # descending
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # a sorted position is kept while the mass BEFORE it is < top_p
    keep_sorted = (cum - probs) < top_p
    inv = jnp.argsort(order, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
    return jnp.where(keep, logits, -1e30)


def apply_repetition_penalty(logits, seen, penalty: float):
    """CTRL-style (HF RepetitionPenaltyLogitsProcessor): for every already-
    seen token, positive logits divide by the penalty, negative multiply."""
    pen = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, pen, logits)


def _sample(logits, rng, temperature: float, top_k: Optional[int],
            top_p: Optional[float] = None):
    """logits [B, V] -> token ids [B]."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p is not None and top_p < 1.0:
        logits = apply_top_p(logits, top_p)
    return jax.random.categorical(rng, logits, axis=-1)


def generate(cfg: TransformerConfig,
             params: PyTree,
             input_ids: jnp.ndarray,
             max_new_tokens: int,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None,
             top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             repetition_penalty: Optional[float] = None,
             attention_mask: Optional[jnp.ndarray] = None,
             kv_cache_dtype: Optional[str] = None) -> jnp.ndarray:
    """Host wrapper over the jitted generation program: validates the
    attention_mask HERE (the shared entry point — benchmarks and library
    users call generate() directly, not only through InferenceEngine).
    HF tokenizers pad RIGHT by default, and a right-padded mask would
    silently decode garbage (the ragged path assumes pads-first). See
    _generate for the full contract."""
    if isinstance(attention_mask, jax.core.Tracer):
        # under an outer jit/vmap/scan the mask is a tracer — host
        # validation is impossible there; inline the jitted program as the
        # pre-wrapper generate() did
        return _generate(cfg, params, input_ids, max_new_tokens,
                         temperature, rng, top_k, top_p, repetition_penalty,
                         attention_mask, kv_cache_dtype)
    if attention_mask is not None:
        # int cast first: np.diff on a BOOL array is XOR (always >= 0), so
        # a bool right-padded mask would sail through the guard
        mask_np = np.asarray(attention_mask, dtype=np.int32)
        if not (np.diff(mask_np, axis=1) >= 0).all():
            raise ValueError(
                "generate() requires LEFT-padded prompts: every "
                "attention_mask row must be non-decreasing (0s then 1s). "
                "Re-tokenize with padding_side='left'.")
        if mask_np.all():
            # uniform batch: dropping the mask keeps the Pallas decode
            # kernel engaged (per-sample masks force the jnp fallback)
            attention_mask = None
        else:
            attention_mask = jnp.asarray(mask_np)
    return _generate(cfg, params, input_ids, max_new_tokens, temperature,
                     rng, top_k, top_p, repetition_penalty, attention_mask,
                     kv_cache_dtype)


@partial(jax.jit, static_argnums=(0, 3, 4, 6, 7, 8, 10))
def _generate(cfg: TransformerConfig,
             params: PyTree,
             input_ids: jnp.ndarray,
             max_new_tokens: int,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None,
             top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             repetition_penalty: Optional[float] = None,
             attention_mask: Optional[jnp.ndarray] = None,
             kv_cache_dtype: Optional[str] = None) -> jnp.ndarray:
    """Prefill + single-token decode loop, one compiled program.

    input_ids [B, T_prompt] -> [B, T_prompt + max_new_tokens].

    Ragged batches: pass ``attention_mask`` [B, T_prompt] with prompts
    LEFT-padded (pads first — the layout where every sample's last prompt
    token sits at the same slot, so one batched prefill serves mixed
    context lengths); positions and attention are pad-corrected per sample,
    matching HF's left-padded batched generate.

    Sampling: temperature / top_k / top_p (nucleus) compose in the HF
    processor order (temperature, then k, then p); ``repetition_penalty``
    applies the CTRL rescale to every token already in the sample's prompt
    or generation.
    """
    B, T_in = input_ids.shape
    max_len = T_in + max_new_tokens
    if max_len > cfg.max_seq_len:
        raise ValueError(f"generation length {max_len} exceeds max_seq_len "
                         f"{cfg.max_seq_len}")
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    params = ensure_scan_layout(params, cfg.num_layers)
    pad_lens = None
    if attention_mask is not None:
        pad_lens = (T_in - jnp.sum(attention_mask.astype(jnp.int32), axis=1)
                    ).astype(jnp.int32)
    # round the workspace up to a decode-kernel-friendly block multiple
    # (positions past the logical max are masked, never attended).
    # kv_cache_dtype="int8": half the KV HBM (2x context/batch capacity),
    # dequant-on-read attention — see init_cache.
    kv_dtype = jnp.int8 if kv_cache_dtype == "int8" else None
    padded_len = padded_cache_len(max_len)
    cache = init_cache(cfg, B, padded_len, dtype=kv_dtype,
                       pad_lens=pad_lens)
    # static routing hint for the decode kernel: batched long generation
    # (most of the preallocated cache dead through the run) is its regime
    prefer_kernel = (B >= 2 and padded_len >= 4 * 512
                     and T_in <= padded_len // 2)
    # the first forward runs against the freshly-initialized (empty) cache:
    # prefill attention rides the flash kernel where eligible
    logits, cache = forward_with_cache(cfg, params, input_ids, cache,
                                       prefer_kernel=prefer_kernel,
                                       prefill_flash=True)

    rep = repetition_penalty is not None and repetition_penalty != 1.0
    if rep:
        # seen-token table [B, V]: every prompt token INCLUDING pads (HF's
        # RepetitionPenaltyLogitsProcessor penalizes the pad id of a
        # left-padded batch too — parity means reproducing that), updated
        # with each generated token. Direct scatter — a one_hot here would
        # materialize a [B, T, V] transient.
        seen = jnp.zeros((B, cfg.vocab_size), jnp.bool_).at[
            jnp.arange(B)[:, None], input_ids].set(True)
    else:
        seen = jnp.zeros((B, 1), jnp.bool_)     # placeholder carry

    def pick(logits_last, seen, r):
        if rep:
            logits_last = apply_repetition_penalty(logits_last, seen,
                                                   repetition_penalty)
        tok = _sample(logits_last, r, temperature, top_k, top_p)
        if rep:
            seen = seen | jax.nn.one_hot(tok, cfg.vocab_size,
                                         dtype=jnp.bool_)
        return tok, seen

    rng, r0 = jax.random.split(rng)
    tok, seen = pick(logits[:, -1], seen, r0)

    def step(carry, _):
        tok, cache, rng, seen = carry
        logits, cache = forward_with_cache(cfg, params, tok[:, None], cache,
                                           prefer_kernel=prefer_kernel)
        rng, r = jax.random.split(rng)
        nxt, seen = pick(logits[:, -1], seen, r)
        return (nxt, cache, rng, seen), tok

    (last, _, _, _), toks = jax.lax.scan(
        step, (tok, cache, rng, seen), None, length=max_new_tokens - 1)
    out = jnp.concatenate([toks.T, last[:, None]], axis=1)  # [B, max_new]
    return jnp.concatenate([input_ids, out], axis=1)
